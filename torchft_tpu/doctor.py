"""Environment diagnostic: ``python -m torchft_tpu.doctor``.

One command an operator runs on a fresh host (or in a wedged job's
postmortem) to answer "is this machine able to run a torchft_tpu replica
group right now": native control plane builds and serves, JAX backend
initializes (with a subprocess probe so a wedged TPU tunnel reports as
WEDGED instead of hanging the doctor — the failure mode bench.py's
`_probe_accelerator` exists for), the virtual multi-device CPU mesh works
(what tests and dryruns rely on), a lighthouse round-trip completes, the
``TORCHFT_RETRY_*`` env knobs are sane (parseable, and the worst-case
backoff budget ordered below the quorum timeout), the ``TORCHFT_HEALTH_*``
healthwatch knobs validate (eject above warn, probation window wide enough
for probe heartbeats to land) with a loopback ``GET /health`` probe of the
lighthouse ledger endpoint, the ``TORCHFT_TRACE_*`` tracing knobs validate
strictly (with a writability probe of the trace dump dir) and both
Prometheus ``/metrics`` exporters (lighthouse native + manager-side
Python) answer a loopback scrape with parseable text, and a loopback
live-heal round-trip through the default HTTP transport lands in place —
with one mid-transfer connection drop injected so the ranged-resume path
(the tier-1 recovery behavior a rejoining replica depends on) is
exercised, not just the happy path. The ``TORCHFT_REDUNDANCY_*`` knobs
validate (k/m sanity plus a live-peer count against k+m when a directory
is configured) and a loopback erasure round-trip encodes a state, corrupts
one stored shard, and reconstructs bitwise via the parity shard. The
``TORCHFT_DEGRADE_*`` knobs validate and a loopback 2→1 reshard probe
asserts the degrade plane's bitwise param-equality invariant on both
engine paths.

Exit code 0 iff every check passes (the accelerator check passes as
"cpu-only" — a legitimate dev box). Prints one line per check:

    ok   native          built (.../libtorchft_tpu.so)
    ok   accelerator     tpu (1 device)
    ...
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Callable, List, Tuple

# (status, detail); status: True=ok, False=fail, None=warn
Result = Tuple["bool | None", str]


def check_native() -> Result:
    try:
        from torchft_tpu.coordination import ensure_native_built

        return True, f"built ({ensure_native_built()})"
    except Exception as e:  # noqa: BLE001
        return False, f"native build/load failed: {e}"


def check_accelerator(timeout_s: float = 60.0) -> Result:
    """Subprocess probe: a wedged TPU tunnel hangs backend init forever."""
    from torchft_tpu.utils import probe_backend

    status, detail = probe_backend(timeout_s)
    if status == "hung":
        return False, (
            f"{detail} — wedged accelerator tunnel? (CPU-only work still "
            "fine via force_virtual_cpu_devices)"
        )
    if status == "crash":
        return False, f"backend init crashed: {detail}"
    if status == "cpu":
        return None, "cpu only (no accelerator — fine for a dev box)"
    return True, detail


def check_virtual_mesh(timeout_s: float = 120.0) -> Result:
    """The 8-device CPU mesh that tests/dryruns use."""
    code = (
        "from torchft_tpu.utils import force_virtual_cpu_devices\n"
        "force_virtual_cpu_devices(8)\n"
        "import jax, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "import numpy as np\n"
        "mesh = Mesh(np.array(jax.devices()[:8]), ('x',))\n"
        "y = jax.device_put(jnp.arange(8.0), NamedSharding(mesh, P('x')))\n"
        "assert float(y.sum()) == 28.0\n"
        "print('ok')\n"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
    except subprocess.TimeoutExpired:
        return False, f"virtual mesh hung >{timeout_s:.0f}s"
    if out.returncode != 0:
        return False, f"virtual mesh failed: {out.stderr.strip()[-200:]}"
    return True, "8-device CPU mesh shards + reduces"


def check_lighthouse_roundtrip() -> Result:
    try:
        from torchft_tpu.coordination import LighthouseClient, LighthouseServer

        lh = LighthouseServer(
            bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=500,
            quorum_tick_ms=20, heartbeat_timeout_ms=2000,
        )
        try:
            client = LighthouseClient(
                f"127.0.0.1:{lh.port}", connect_timeout=5.0
            )
            client.heartbeat("doctor", timeout=5.0)
            q = client.quorum(replica_id="doctor", timeout=10.0)
            ok = any(m.replica_id == "doctor" for m in q.participants)
            return (True, f"quorum_id={q.quorum_id} formed") if ok else (
                False, "quorum formed without this replica"
            )
        finally:
            lh.shutdown()
    except Exception as e:  # noqa: BLE001
        return False, f"lighthouse round-trip failed: {e}"


def check_retry_env() -> Result:
    """TORCHFT_RETRY_* env sanity: the values parse, and the worst-case
    retry sleep budget is ordered BELOW the quorum timeout — a backoff
    schedule that can out-sleep the quorum window turns every control-plane
    blip into a quorum failure instead of a slower step."""
    try:
        from torchft_tpu.retry import RetryPolicy

        policy = RetryPolicy.from_env()
    except ValueError as e:
        return False, f"TORCHFT_RETRY_* env invalid: {e}"
    quorum_timeout_s = float(
        os.environ.get(
            "TORCHFT_QUORUM_TIMEOUT_SEC",
            os.environ.get("TORCHFT_TIMEOUT_SEC", "60.0"),
        )
    )
    # worst case: every sleep hits the ceiling, jitter draws nothing
    worst_sleep_s = sum(
        policy.backoff_s(attempt) for attempt in range(2, policy.max_attempts + 1)
    )
    detail = (
        f"attempts={policy.max_attempts} base={policy.base_s}s "
        f"ceiling={policy.max_backoff_s}s jitter={policy.jitter} "
        f"(worst sleep {worst_sleep_s:.2f}s vs quorum {quorum_timeout_s:.0f}s)"
    )
    if policy.max_backoff_s >= quorum_timeout_s:
        return False, (
            f"backoff ceiling {policy.max_backoff_s}s >= quorum timeout "
            f"{quorum_timeout_s}s — one retry sleep can eat the whole "
            "quorum window; lower TORCHFT_RETRY_MAX_BACKOFF_S"
        )
    if worst_sleep_s >= quorum_timeout_s:
        return None, (
            f"worst-case retry sleep {worst_sleep_s:.2f}s >= quorum "
            f"timeout {quorum_timeout_s}s — retries may burn the quorum "
            "window sleeping; lower TORCHFT_RETRY_MAX_ATTEMPTS or the "
            "backoff knobs"
        )
    if not policy.enabled:
        return None, f"retries disabled (max_attempts=1); {detail}"
    return True, detail


def check_health_env() -> Result:
    """TORCHFT_HEALTH_* env sanity: the knobs parse and validate (which
    enforces eject_z > warn_z — ordered thresholds are what makes warn an
    early warning), and the probation window is long enough to actually
    observe recovery: readmission needs probe heartbeats to land INSIDE
    the window, so probation_ms must comfortably exceed the heartbeat
    interval or a readmitted replica is judged on zero samples."""
    try:
        from torchft_tpu.healthwatch import HealthConfig

        config = HealthConfig.from_env()
    except ValueError as e:
        return False, f"TORCHFT_HEALTH_* env invalid: {e}"
    detail = (
        f"mode={config.mode} warn_z={config.warn_z} eject_z={config.eject_z} "
        f"eject_steps={config.eject_steps} probation_ms={config.probation_ms}"
    )
    if config.mode == "off":
        return None, f"healthwatch disabled; {detail}"
    # the default Manager heartbeat interval (manager.py) — the cadence
    # probe beats arrive at during probation
    heartbeat_ms = float(os.environ.get("TORCHFT_HEARTBEAT_INTERVAL_MS", "100"))
    if config.probation_ms <= heartbeat_ms:
        return False, (
            f"TORCHFT_HEALTH_PROBATION_MS={config.probation_ms} <= heartbeat "
            f"interval {heartbeat_ms:.0f}ms — the probation window closes "
            "before a single probe heartbeat lands; raise it"
        )
    if config.probation_ms < heartbeat_ms * config.probe_ok:
        return None, (
            f"probation_ms={config.probation_ms} < heartbeat interval × "
            f"probe_ok ({heartbeat_ms:.0f}×{config.probe_ok}) — readmission "
            "may need several windows; consider raising it"
        )
    return True, detail


def check_compress_env() -> Result:
    """``TORCHFT_COMPRESS`` sanity: the value resolves to a known codec
    (funnelled through the same ``resolve_compress_mode`` the Manager
    uses, so the doctor and the trainer reject identically), and if
    compression is ON while bucket streaming is forced OFF the operator
    is warned — compressed buckets ride the streaming pipeline, so the
    knob silently does nothing for unquantized trees without it."""
    try:
        from torchft_tpu.ops.quantization import resolve_compress_mode

        mode = resolve_compress_mode()
    except ValueError as e:
        return False, (
            f"TORCHFT_COMPRESS invalid: {e}; unset it or pick one of "
            "off/fp8/int8"
        )
    if mode == "off":
        return True, "compression off (default wire, bit-identical path)"
    stream_raw = os.environ.get("TORCHFT_STREAM_BUCKETS", "").strip().lower()
    if stream_raw in ("0", "false", "no", "off"):
        return None, (
            f"TORCHFT_COMPRESS={mode} but TORCHFT_STREAM_BUCKETS="
            f"{stream_raw!r} disables the streaming pipeline compression "
            "rides — buckets will ship uncompressed; re-enable streaming "
            "or unset TORCHFT_COMPRESS"
        )
    return True, f"compression {mode} (rowwise codec, error feedback on)"


def check_health_endpoint() -> Result:
    """Loopback /health probe: a lighthouse with the healthwatch ledger
    enabled serves the JSON an operator's dashboard would scrape, and the
    payload reflects a heartbeat it just ingested."""
    try:
        import json as _json
        import urllib.request

        from torchft_tpu.coordination import LighthouseClient, LighthouseServer

        lh = LighthouseServer(
            bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=500,
            quorum_tick_ms=20, heartbeat_timeout_ms=2000,
            health={"mode": "observe"},
        )
        try:
            client = LighthouseClient(f"127.0.0.1:{lh.port}", connect_timeout=5.0)
            client.heartbeat(
                "doctor", timeout=5.0,
                telemetry={"step": 1, "step_s": 0.1, "wire_s": 0.01},
            )
            with urllib.request.urlopen(
                f"http://127.0.0.1:{lh.port}/health", timeout=5.0
            ) as resp:
                payload = _json.loads(resp.read().decode())
        finally:
            lh.shutdown()
        if "doctor" not in payload.get("replicas", {}):
            return False, f"/health missing the beating replica: {payload}"
        return True, (
            f"/health serves mode={payload.get('mode')} "
            f"({len(payload.get('replicas', {}))} replica tracked)"
        )
    except Exception as e:  # noqa: BLE001
        return False, f"/health probe failed: {e}"


def check_heal_roundtrip() -> Result:
    """Loopback live-heal: send a small composite through the default
    HTTPTransport and receive it in place — the tier-1 recovery path a
    rejoining replica depends on. The serve of chunk 1 is armed to drop
    mid-transfer once, so the check also exercises one ranged re-fetch:
    the receiver must resume from its last verified byte, not restart."""
    try:
        import numpy as np

        from torchft_tpu.checkpointing import HTTPTransport
        from torchft_tpu.retry import RetryPolicy

        state = {"user": {"w": np.arange(256, dtype=np.float32)},
                 "torchft": {"step": 3, "batches_committed": 6}}
        template = {"user": {"w": np.zeros(256, np.float32)},
                    "torchft": {"step": 0, "batches_committed": 0}}
        # pin loopback: gethostname() can be locally unresolvable on
        # minimal containers (the fleet problem `hostname` exists for),
        # and this check diagnoses the transport, not DNS
        send = HTTPTransport(timeout=10.0, num_chunks=2,
                             hostname="127.0.0.1")
        # explicit policy: the check must re-fetch deterministically even
        # when the operator's env disables retries (that env shape is
        # check_retry_env's job to flag, not this one's to inherit)
        recv = HTTPTransport(timeout=10.0,
                             state_dict_template=lambda: template,
                             retry_policy=RetryPolicy(
                                 max_attempts=3, base_s=0.01, jitter=0.0))
        events: list = []
        try:
            send.send_checkpoint([1], 3, state, 10.0)
            send.inject_chunk_fault(1, "die", times=1)
            got = recv.recv_checkpoint_multi(
                [("loopback", send.metadata)], 3, 10.0,
                on_event=lambda kind, **f: events.append((kind, f)),
            )
        finally:
            send.shutdown()
            recv.shutdown()
        if got["user"]["w"] is not template["user"]["w"]:
            return False, "heal received but not in place (template unused)"
        if not np.array_equal(got["user"]["w"], state["user"]["w"]):
            return False, "heal payload mismatch"
        resumed = [
            f for kind, f in events
            if kind == "heal_retry" and f.get("resume_offset", 0) > 0
        ]
        if not resumed:
            return False, (
                "mid-transfer drop never produced a ranged resume "
                f"(events: {[k for k, _ in events]})"
            )
        return True, (
            "http heal round-trip in place; ranged re-fetch resumed at "
            f"byte {resumed[0]['resume_offset']}"
        )
    except Exception as e:  # noqa: BLE001
        return False, f"heal round-trip failed: {e}"


def check_trace_env() -> Result:
    """``TORCHFT_TRACE_*`` env sanity, validated STRICTLY (the Manager's
    ``TraceConfig.from_env`` falls back to defaults on garbage so a typo
    can't kill a trainer — which is exactly why the doctor must flag it:
    silently-defaulted knobs are the ones operators chase for hours), plus
    a writability probe of the configured dump directory — an unwritable
    dump dir only surfaces at the worst moment (a postmortem auto-dump)."""
    from torchft_tpu.tracing import (
        TRACE_BUFFER_ENV,
        TRACE_DIR_ENV,
        TRACE_ENV,
        TRACE_SAMPLE_ENV,
        TraceConfig,
    )

    raw_buffer = os.environ.get(TRACE_BUFFER_ENV, "")
    if raw_buffer:
        try:
            buf = int(raw_buffer)
        except ValueError:
            return False, (
                f"{TRACE_BUFFER_ENV}={raw_buffer!r} is not an integer — the "
                "Manager silently falls back to the default ring size"
            )
        if buf < 16:
            return None, (
                f"{TRACE_BUFFER_ENV}={buf} below the floor of 16 — clamped; "
                "a ring that small drops most of a step's spans"
            )
    raw_sample = os.environ.get(TRACE_SAMPLE_ENV, "")
    if raw_sample:
        try:
            sample = float(raw_sample)
        except ValueError:
            return False, (
                f"{TRACE_SAMPLE_ENV}={raw_sample!r} is not a float — the "
                "Manager silently falls back to sampling every step"
            )
        if not 0.0 <= sample <= 1.0:
            return None, (
                f"{TRACE_SAMPLE_ENV}={sample} outside [0, 1] — clamped"
            )
    cfg = TraceConfig.from_env()
    if cfg.dump_dir:
        try:
            os.makedirs(cfg.dump_dir, exist_ok=True)
            probe = os.path.join(cfg.dump_dir, ".doctor_probe")
            with open(probe, "w") as f:
                f.write("ok")
            os.remove(probe)
        except OSError as e:
            return False, (
                f"{TRACE_DIR_ENV}={cfg.dump_dir!r} not writable ({e}) — "
                "postmortem trace auto-dumps will be lost"
            )
    detail = (
        f"enabled={cfg.enabled} buffer={cfg.buffer} sample={cfg.sample} "
        f"dump_dir={cfg.dump_dir or '(flight-recorder fallback)'}"
    )
    if not cfg.enabled:
        return None, f"tracing disabled ({TRACE_ENV}); {detail}"
    return True, detail


def _parse_prometheus(text: str) -> "dict[str, float]":
    """Minimal exposition-format parse: series name (labels folded in) ->
    value. Raises on malformed lines, which is the point of the probe."""
    series = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, value = line.rsplit(" ", 1)
        series[name] = float(value)
    return series


def check_metrics_endpoints() -> Result:
    """Loopback /metrics probes of BOTH exporters: the lighthouse's native
    endpoint (beside /health) and the manager-side Python MetricsServer.
    Each response must parse as Prometheus text and carry its signature
    series — a scrape config written against docs/observability.md works."""
    try:
        import urllib.request

        from torchft_tpu.coordination import LighthouseClient, LighthouseServer
        from torchft_tpu.observability import MetricsRegistry, MetricsServer

        lh = LighthouseServer(
            bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=500,
            quorum_tick_ms=20, heartbeat_timeout_ms=2000,
            health={"mode": "observe"},
        )
        try:
            client = LighthouseClient(f"127.0.0.1:{lh.port}", connect_timeout=5.0)
            client.heartbeat(
                "doctor", timeout=5.0,
                telemetry={"step": 1, "step_s": 0.1, "wire_s": 0.01},
            )
            with urllib.request.urlopen(
                f"http://127.0.0.1:{lh.port}/metrics", timeout=5.0
            ) as resp:
                lh_series = _parse_prometheus(resp.read().decode())
        finally:
            lh.shutdown()
        if "torchft_lighthouse_fleet_size" not in lh_series:
            return False, (
                "lighthouse /metrics parsed but is missing "
                f"torchft_lighthouse_fleet_size: {sorted(lh_series)[:5]}..."
            )
        registry = MetricsRegistry()
        registry.gauge_set("torchft_doctor_probe", 1.0, "Doctor loopback.")
        server = MetricsServer(registry, port=0)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=5.0
            ) as resp:
                mgr_series = _parse_prometheus(resp.read().decode())
        finally:
            server.shutdown()
        if mgr_series.get("torchft_doctor_probe") != 1.0:
            return False, f"manager-side /metrics lost the probe gauge: {mgr_series}"
        return True, (
            f"lighthouse /metrics ({len(lh_series)} series) + manager "
            f"/metrics both parse as Prometheus text"
        )
    except Exception as e:  # noqa: BLE001
        return False, f"/metrics probe failed: {e}"


def check_aggregator() -> Result:
    """Two-level control plane: validate the TORCHFT_LIGHTHOUSE_AGGREGATOR
    wiring, then prove the aggregator path works end to end on loopback —
    a beat sent to an AggregatorServer must surface at the root lighthouse
    via a batched agg_tick (not a direct heartbeat)."""
    import time as _time

    try:
        from torchft_tpu.coordination import (
            AggregatorServer,
            LighthouseClient,
            LighthouseServer,
        )
        from torchft_tpu.manager import AGGREGATOR_ENV, LIGHTHOUSE_ENV

        env_note = "flat fleet (no aggregator env)"
        agg_env = os.environ.get(AGGREGATOR_ENV, "")
        if agg_env:
            host, sep, port = agg_env.replace("http://", "").rpartition(":")
            if not sep or not host or not port.isdigit():
                return False, (
                    f"{AGGREGATOR_ENV}={agg_env!r} is not host:port — "
                    "managers will fail to start"
                )
            if not os.environ.get(LIGHTHOUSE_ENV, ""):
                return False, (
                    f"{AGGREGATOR_ENV} is set but {LIGHTHOUSE_ENV} is not: "
                    "the pod cannot fail over to the root if its "
                    "aggregator dies — set both"
                )
            env_note = f"two-level ({agg_env} -> {os.environ[LIGHTHOUSE_ENV]})"

        root = LighthouseServer(
            bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=500,
            quorum_tick_ms=20, heartbeat_timeout_ms=2000,
        )
        agg = None
        try:
            agg = AggregatorServer(
                root_addr=f"127.0.0.1:{root.port}", bind="127.0.0.1:0",
                agg_id="doctor_pod", tick_ms=50,
            )
            pod_client = LighthouseClient(
                f"127.0.0.1:{agg.port}", connect_timeout=5.0
            )
            resp = pod_client.heartbeat("doctor", timeout=5.0)
            if not resp.get("aggregated"):
                return False, "aggregator heartbeat response not marked aggregated"
            root_client = LighthouseClient(
                f"127.0.0.1:{root.port}", connect_timeout=5.0
            )
            deadline = _time.monotonic() + 10.0
            while _time.monotonic() < deadline:
                st = root_client.status(timeout=5.0)
                if "doctor" in st.get("heartbeat_ages_ms", {}):
                    if st.get("rx", {}).get("heartbeat", {}).get("calls", 0):
                        return False, (
                            "beat reached the root as a DIRECT heartbeat — "
                            "the aggregator forwarded instead of batching"
                        )
                    ticks = st["aggregators"]["doctor_pod"]["ticks"]
                    return True, (
                        f"{env_note}; loopback pod beat surfaced at root "
                        f"via agg_tick (ticks={ticks})"
                    )
                _time.sleep(0.1)
            return False, "pod beat never surfaced at the root within 10s"
        finally:
            if agg is not None:
                agg.shutdown()
            root.shutdown()
    except Exception as e:  # noqa: BLE001
        return False, f"aggregator probe failed: {e}"


def check_serve_env() -> Result:
    """``TORCHFT_SERVE_*`` sanity: the env contract parses into a valid
    ServeConfig (same validation path the worker CLI, the registry, and
    the publisher all funnel through — doctor and serving plane reject
    identically).  A configured-but-unreachable registry is a warn, not a
    fail: the serving plane is optional and workers retry."""
    try:
        from torchft_tpu.serving import ServeConfig

        cfg = ServeConfig.from_env()
    except ValueError as e:
        return False, f"TORCHFT_SERVE_* invalid: {e}"
    if not cfg.registry:
        return True, (
            f"serving plane unconfigured (compress={cfg.compress}, "
            f"max_lag={cfg.max_lag}, drain_on={cfg.drain_on}); set "
            "TORCHFT_SERVE_REGISTRY to enable"
        )
    import urllib.request

    try:
        with urllib.request.urlopen(
            f"{cfg.registry.rstrip('/')}/serve/sources", timeout=3.0
        ) as r:
            listing = json.loads(r.read().decode())
    except Exception as e:  # noqa: BLE001 — unreachable is a warn
        return None, (
            f"TORCHFT_SERVE_REGISTRY={cfg.registry} unreachable ({e!r}); "
            "workers will retry, but check the lighthouse --serve-registry "
            "flag / the registry process"
        )
    return True, (
        f"registry at {cfg.registry}: {len(listing.get('sources', []))} "
        f"source(s), latest={listing.get('latest')}, "
        f"epoch={listing.get('epoch')}"
    )


def check_serving_roundtrip() -> Result:
    """Loopback serving probe: registry + one publisher + one worker pull.
    Publishes two tiny versions, asserts the worker lands on the newest
    via a full pull then a compressed delta, bitwise-equal to the
    publisher's reference — the whole plane (announce, source ordering,
    ranged full pull, delta walk, error-feedback replay) in one breath."""
    import numpy as np

    from torchft_tpu.serving import (
        ServeConfig,
        ServeWorker,
        SnapshotPublisher,
        SnapshotRegistry,
    )

    registry = SnapshotRegistry()
    cfg = ServeConfig(
        registry=registry.url, max_lag=4, compress="fp8",
        poll_s=0.02, timeout_s=10.0,
    )
    publisher = SnapshotPublisher(
        "doctor_replica", config=cfg, registry_url=registry.url
    )
    worker = ServeWorker(registry.url, config=cfg, name="doctor_worker")
    try:
        rng = np.random.RandomState(7)
        params = {"w": rng.randn(4096).astype(np.float32)}
        publisher.publish(1, 0, params)
        if not worker.wait_version((1, 0), timeout=10.0):
            return False, (
                f"worker never reached (1, 0): counters={worker.counters}"
            )
        params["w"] = params["w"] + 0.01
        publisher.publish(1, 1, params)
        if not worker.wait_version((1, 1), timeout=10.0):
            return False, (
                f"worker stuck at {worker.version} (want (1, 1)): "
                f"counters={worker.counters}"
            )
        if not np.array_equal(worker.params_flat(), publisher.ref_flat()):
            return False, (
                "worker params != publisher reference after pull — the "
                "bitwise delta/full invariant broke"
            )
        c = worker.counters
        return True, (
            f"worker converged to (1, 1): {c['full_pulls_total']} full + "
            f"{c['delta_pulls_total']} delta pull(s), "
            f"{c['delta_bytes_total']}B delta vs {c['full_bytes_total']}B full"
        )
    finally:
        worker.shutdown()
        publisher.shutdown()
        registry.shutdown()


def check_redundancy_env() -> Result:
    """``TORCHFT_REDUNDANCY_*`` sanity: the env contract parses into a
    valid RedundancyConfig (same validation the Manager funnels through),
    and when the plane is on, the shard directory answers and holds
    enough live non-spare peers for k+m distinct shard holders. Too few
    peers is a warn, not a fail: placement wraps and the plane still
    works — with degraded distinct-peer durability."""
    try:
        from torchft_tpu.redundancy import DirectoryClient, RedundancyConfig

        cfg = RedundancyConfig.from_env()
    except ValueError as e:
        return False, f"TORCHFT_REDUNDANCY_* invalid: {e}"
    if cfg.k == 0:
        return True, (
            "redundancy plane off (k=0 — peer heal only); set "
            "TORCHFT_REDUNDANCY_K/_M/_DIRECTORY to enable erasure staging"
        )
    if not cfg.directory:
        return None, (
            f"TORCHFT_REDUNDANCY_K={cfg.k} but no "
            "TORCHFT_REDUNDANCY_DIRECTORY — staging stays off; point it at "
            "the lighthouse's /redundancy endpoint"
        )
    try:
        peers = DirectoryClient(cfg.directory, timeout=3.0).peers()
    except Exception as e:  # noqa: BLE001 — unreachable is a warn
        return None, (
            f"TORCHFT_REDUNDANCY_DIRECTORY={cfg.directory} unreachable "
            f"({e!r}); stagers retry, but check the lighthouse "
            "--redundancy-directory flag / the directory process"
        )
    live = [p for p in peers if not p.get("spare")]
    if len(live) < cfg.k + cfg.m:
        return None, (
            f"k+m={cfg.k + cfg.m} but only {len(live)} live non-spare "
            "peer(s) registered — placement wraps holders; distinct-peer "
            "durability degraded until the fleet grows"
        )
    return True, (
        f"k={cfg.k} m={cfg.m} interval={cfg.interval}, directory at "
        f"{cfg.directory}: {len(live)} live peer(s), "
        f"{len(peers) - len(live)} spare(s)"
    )


def check_degrade_env() -> Result:
    """``TORCHFT_DEGRADE_*`` sanity: the env contract parses into a valid
    DegradeConfig (same validation the Manager funnels through), and a
    loopback 2→1 reshard probe runs both engine paths — full
    redistribution and gather-free peer-sourced — asserting the shrunken
    layout reassembles bitwise-identical to the original params (the
    invariant the degrade plane's correctness rests on)."""
    try:
        from torchft_tpu.parallel.degrade import DegradeConfig

        cfg = DegradeConfig.from_env()
    except ValueError as e:
        return False, f"TORCHFT_DEGRADE_* invalid: {e}"
    try:
        import numpy as np

        from torchft_tpu.parallel.degrade import (
            assemble,
            reshard_from_survivors,
            reshard_full,
        )

        rng = np.random.default_rng(0)
        full = {
            "w": rng.standard_normal((6, 4)).astype(np.float32),
            "b": rng.standard_normal((3,)).astype(np.float32),
        }
        axes = {"w": 0, "b": None}
        two_chip, _ = reshard_full(full, axes, 2)
        # full path: 2 -> 1
        one_chip, _ = reshard_full(full, axes, 1)
        back = assemble(one_chip, axes)
        if not all(
            np.array_equal(back[k], full[k]) for k in full
        ):
            return False, "full-path 2->1 reshard probe not bitwise equal"
        # peer path: kill rank 1, source its shard from the old layout
        dead_shards = {"w": np.asarray(two_chip[1]["w"])}
        survivors, _ = reshard_from_survivors(
            [two_chip[0], None],
            dead_rank=1,
            axes=axes,
            shard_source=lambda path: dead_shards["w"],
        )
        back = assemble(survivors, axes)
        if not all(
            np.array_equal(back[k], full[k]) for k in full
        ):
            return False, "peer-path 2->1 reshard probe not bitwise equal"
    except Exception as e:  # noqa: BLE001
        return False, f"degrade reshard probe failed: {e}"
    if not cfg.enabled:
        return True, (
            "degrade plane off (TORCHFT_DEGRADE=off — chip loss costs the "
            "whole replica); reshard probe bitwise ok"
        )
    return True, (
        f"on: min_degree={cfg.min_degree} restore={cfg.restore}; "
        "2->1 reshard probe bitwise ok (full + peer paths)"
    )


def check_redundancy_roundtrip() -> Result:
    """Loopback redundancy probe: encode a state across k=2/m=1 shards on
    three stores, corrupt one data shard's stored bytes, and reconstruct —
    crc32 must catch the corruption and the parity shard must repair it to
    a bitwise-identical state. The whole plane (placement announce, shard
    GETs, corrupt-shard detection, GF(256) decode) in one breath."""
    import numpy as np

    from torchft_tpu.checkpointing.erasure import encode_shards, shard_crc
    from torchft_tpu.redundancy import (
        DirectoryClient,
        ShardDirectory,
        ShardStore,
        pack_state_blob,
        put_shard,
        reconstruct_state,
    )

    k, m = 2, 1
    directory = ShardDirectory()
    client = DirectoryClient(directory.url, timeout=5.0)
    stores = [ShardStore(f"doctor_holder_{i}") for i in range(k + m)]
    try:
        rng = np.random.RandomState(11)
        state = {"w": rng.randn(65536).astype(np.float32)}
        blob = pack_state_blob(state)
        shards = encode_shards(blob, k, m)
        epoch = client.register("doctor_red", "doctor", stores[0].url)
        entries = []
        for idx, body in enumerate(shards):
            # shard 0 is stored corrupted but announced with the true crc:
            # the GET must fail verification, not silently decode garbage
            stored = (bytes([body[0] ^ 0xFF]) + body[1:]) if idx == 0 else body
            put_shard(stores[idx].url, "doctor_red", 1, idx, stored, timeout=5.0)
            entries.append({
                "idx": idx, "holder": stores[idx].replica_id,
                "url": stores[idx].url, "crc": shard_crc(body),
            })
        code, resp = client.announce({
            "replica_id": "doctor_red", "epoch": epoch, "seq": 1, "step": 1,
            "k": k, "m": m, "data_len": len(blob), "shards": entries,
        })
        if code != 200:
            return False, f"directory rejected announce: {resp}"
        _, got, stats = reconstruct_state(
            directory.url, owner="doctor_red", timeout=30.0
        )
        if stats.get("shards_corrupt", 0) < 1:
            return False, (
                "corrupted shard was not detected — crc32 verification on "
                f"the shard GET path regressed (stats={stats})"
            )
        if not np.array_equal(np.asarray(got["w"]), state["w"]):
            return False, (
                "reconstructed state != original — GF(256) parity repair "
                "broke the bitwise round-trip"
            )
        return True, (
            f"k={k}+m={m} reconstruct repaired 1 corrupt shard bitwise "
            f"({stats['shards_ok']} ok / {stats['shards_corrupt']} corrupt, "
            f"{stats['mb_per_s']:.0f} MB/s loopback)"
        )
    finally:
        for s in stores:
            s.shutdown()
        directory.shutdown()


def check_tuning_env() -> Result:
    """Registry-driven sanity for every tuning knob that has no
    plane-specific doctor check: each ``TORCHFT_*`` value set in the
    environment must parse per its declared type in the knob registry
    (torchft_tpu/knobs.py), JSON knobs must decode to objects, and enums
    must name a declared member. Catches the classic fleet-rollout typo
    (``TORCHFT_BUCKET_CAP_MB=32mb``) before it silently falls back."""
    from torchft_tpu import knobs

    checked = 0
    problems: List[str] = []
    for name, knob in sorted(knobs.all_knobs().items()):
        if knob.doctor != "tuning-env":
            continue
        raw = os.environ.get(name)
        checked += 1
        if raw is None or raw.strip() == "":
            continue
        try:
            if knob.type == "int":
                int(raw)
            elif knob.type == "float":
                float(raw)
            elif knob.type == "bool":
                if raw.strip().lower() not in (
                    "0", "1", "true", "false", "yes", "no", "on", "off"
                ):
                    raise ValueError(f"not a boolean: {raw!r}")
            elif knob.type.startswith("enum("):
                members = knob.type[5:-1].split("|")
                if raw not in members:
                    raise ValueError(f"{raw!r} not in {members}")
            elif name.endswith("_JSON"):
                if not isinstance(json.loads(raw), dict):
                    raise ValueError("must decode to a JSON object")
        except ValueError as e:
            problems.append(f"{name}={raw!r} ({e})")
    if problems:
        return False, "; ".join(problems)
    n_set = sum(
        1
        for name, knob in knobs.all_knobs().items()
        if knob.doctor == "tuning-env" and os.environ.get(name)
    )
    return True, f"{checked} tuning knob(s) registered, {n_set} set, all parse"


def check_policy_env() -> Result:
    """``TORCHFT_POLICY*`` sanity plus a loopback observe probe: the mode
    names a known member, the numeric knobs parse, the spec (builtin or
    the ``TORCHFT_POLICY_SPEC`` file) loads and validates, and a
    throwaway engine in observe mode folds a synthetic churn burst into a
    well-formed frame — the exact fold/evaluate pipeline a lighthouse
    runs live, so a bad spec fails here instead of at fleet start."""
    from torchft_tpu import knobs
    from torchft_tpu.policy import POLICY_MODES, PolicyEngine, PolicySpec

    mode = os.environ.get("TORCHFT_POLICY", "").strip() or "off"
    if mode not in POLICY_MODES:
        return False, (
            f"TORCHFT_POLICY={mode!r} invalid: pick one of "
            f"{'/'.join(POLICY_MODES)}"
        )
    try:
        knobs.env_float("TORCHFT_POLICY_INTERVAL_S", 5.0)
        window_s = knobs.env_float("TORCHFT_POLICY_WINDOW_S", 300.0)
        knobs.env_int("TORCHFT_POLICY_RING", 4096)
        knobs.env_int("TORCHFT_SYNC_EVERY", 0)
    except ValueError as e:
        return False, f"TORCHFT_POLICY_* numeric knob invalid: {e}"
    spec_src = os.environ.get("TORCHFT_POLICY_SPEC", "").strip() or "builtin"
    try:
        spec = PolicySpec.load(spec_src)
    except (ValueError, OSError, KeyError) as e:
        return False, f"policy spec {spec_src!r} failed to load: {e}"
    try:
        # loopback observe probe on synthetic history (no lighthouse, no
        # wall clock): a hot churn burst must fold and evaluate cleanly
        from torchft_tpu._test.event_injector import churn_burst

        engine = PolicyEngine(spec, mode="observe", window_s=window_s)
        engine.feed(churn_burst(8, period_s=5.0))
        frame = engine.evaluate()
        if "policy_seq" not in frame:
            raise ValueError(f"malformed frame: {frame!r}")
    except Exception as e:  # noqa: BLE001 — probe failure is the finding
        return False, f"observe probe failed on spec {spec_src!r}: {e}"
    if mode == "off":
        return True, (
            f"policy off (byte-identical path); spec {spec_src!r} "
            f"validates ({len(spec.rules)} rule(s)) and probes clean"
        )
    return True, (
        f"policy {mode}: spec {spec_src!r} ({len(spec.rules)} rule(s)) "
        f"probed clean, frame seq={frame['policy_seq']}"
    )


def check_fleetlint() -> Result:
    """In-process fleetlint env-contract run: every TORCHFT_* read in the
    package is registered/documented/doctored, and no finding beyond the
    committed baseline (torchft_tpu/analysis/baseline.json). The full
    five-checker run lives in CI (`python -m torchft_tpu.analysis --ci`);
    the env contract is the part that drifts with operator-facing
    surface, so the doctor re-validates it on any host."""
    from torchft_tpu.analysis import core

    findings = core.run_all(checkers=["env-contract"])
    baseline = core.load_baseline()
    new, stale = core.diff_baseline(findings, baseline)
    if new:
        head = "; ".join(f"{f.rule}:{f.key}" for f in new[:5])
        more = f" (+{len(new) - 5} more)" if len(new) > 5 else ""
        return False, (
            f"{len(new)} env-contract finding(s) beyond baseline: "
            f"{head}{more} — run python -m torchft_tpu.analysis"
        )
    detail = (
        f"{len(findings)} finding(s), all baselined"
        if findings
        else "env contract clean"
    )
    if stale:
        return None, f"{detail}; {len(stale)} stale baseline entr(y/ies)"
    return True, detail


CHECKS: List[Tuple[str, Callable[[], Result]]] = [
    ("native", check_native),
    ("accelerator", check_accelerator),
    ("virtual-mesh", check_virtual_mesh),
    ("lighthouse", check_lighthouse_roundtrip),
    ("aggregator", check_aggregator),
    ("retry-env", check_retry_env),
    ("health-env", check_health_env),
    ("compress-env", check_compress_env),
    ("serve-env", check_serve_env),
    ("redundancy-env", check_redundancy_env),
    ("degrade-env", check_degrade_env),
    ("trace-env", check_trace_env),
    ("policy-env", check_policy_env),
    ("tuning-env", check_tuning_env),
    ("fleetlint", check_fleetlint),
    ("health-http", check_health_endpoint),
    ("metrics-http", check_metrics_endpoints),
    ("heal", check_heal_roundtrip),
    ("serving", check_serving_roundtrip),
    ("redundancy", check_redundancy_roundtrip),
]


def main() -> None:
    failed = False
    for name, fn in CHECKS:
        try:
            status, detail = fn()
        except Exception as e:  # noqa: BLE001 - a crashing check is a failure
            status, detail = False, f"check crashed: {e}"
        tag = {True: "ok  ", None: "warn", False: "FAIL"}[status]
        print(f"{tag} {name:<14} {detail}", flush=True)
        failed |= status is False
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
