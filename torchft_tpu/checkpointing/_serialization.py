"""Pytree (de)serialization for checkpoint transports.

Role-equivalent of the reference's streaming torch.save/load
(torchft/checkpointing/_serialization.py:14-39) but for JAX pytrees: the tree
structure and per-leaf metadata travel as a pickled spec; array payloads are
raw little-endian buffers that can be split into chunks and fetched in
parallel (reference chunking: http_transport.py:287-298).
"""

from __future__ import annotations

import io
import pickle
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "TensorMeta",
    "TreeSpecPayload",
    "alloc_leaf",
    "flatten_state",
    "unflatten_state",
    "leaf_to_bytes",
    "leaf_from_bytes",
    "payload_memoryview",
    "can_absorb",
    "place_leaf_like",
    "split_chunks",
    "template_leaves_for",
]


@dataclass
class TensorMeta:
    """Per-leaf metadata (reference: pg_transport.py:32-59 _TensorMeta).

    Layout restore is the receiver's job: transports place received leaves
    onto a caller-provided template's sharding (PGTransport's in-place
    receive) rather than shipping sharding descriptions on the wire.
    """

    dtype: str
    shape: Tuple[int, ...]
    nbytes: int
    kind: str = "array"  # "array" | "pickled" (non-array leaf)


@dataclass
class TreeSpecPayload:
    """Pickled header: tree structure + leaf metadata."""

    treedef_bytes: bytes
    leaves: List[TensorMeta] = field(default_factory=list)


def _is_array(x: Any) -> bool:
    return isinstance(x, np.ndarray) or type(x).__module__.startswith("jax")


def flatten_state(state: Any,
                  snapshot: bool = True) -> Tuple[TreeSpecPayload, List[Any]]:
    """Flatten a pytree into (spec, per-leaf payloads).

    Array leaves (numpy or jax) are staged to host and kept as **arrays**
    (a zero-copy view for numpy inputs; one D2H for jax) — NOT serialized
    to bytes here. Transports stream straight from the array memory, so
    peak host memory stays ~1x the payload instead of the 2-3x that
    pre-serializing every leaf costs (VERDICT round-2 item 6). Non-array
    leaves are pickled bytes.

    ``snapshot=True`` copies numpy leaves so a live tree mutated by the
    training loop can't tear a checkpoint that is still being served
    (HTTP's pull window outlives the call). A transport whose send is
    SYNCHRONOUS — the stream completes before send_checkpoint returns, so
    nothing can mutate the tree mid-stream under the Manager's
    paused-at-quorum heal protocol — passes ``snapshot=False`` and streams
    straight from the caller's memory, saving a full checkpoint copy per
    heal (the reference PGTransport sends from the live tensors the same
    way, pg_transport.py:202-233).
    """
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(state)
    metas: List[TensorMeta] = []
    payloads: List[Any] = []
    for leaf in leaves:
        if _is_array(leaf):
            if isinstance(leaf, np.ndarray):
                if snapshot:
                    # a live numpy leaf may be mutated in place by the
                    # training loop while the serving window is open —
                    # streaming an alias would tear the checkpoint mid-leaf
                    host = np.array(leaf, copy=True, order="C")
                else:
                    host = np.ascontiguousarray(leaf)
            else:
                # jax.Array: on accelerators np.asarray materializes a
                # fresh host buffer (one D2H). On the CPU backend it can
                # be a ZERO-COPY alias of the live device buffer, which a
                # later donated step may reuse while the serving window is
                # still streaming — so force ownership when aliased.
                host = np.ascontiguousarray(np.asarray(leaf))
                if host.base is not None or not host.flags.owndata:
                    host = host.copy()
            metas.append(
                TensorMeta(
                    dtype=str(host.dtype),
                    shape=tuple(host.shape),
                    nbytes=host.nbytes,
                )
            )
            payloads.append(host)
        else:
            buf = pickle.dumps(leaf)
            metas.append(
                TensorMeta(dtype="", shape=(), nbytes=len(buf), kind="pickled")
            )
            payloads.append(buf)
    spec = TreeSpecPayload(treedef_bytes=pickle.dumps(treedef), leaves=metas)
    return spec, payloads


def payload_memoryview(payload: Any) -> memoryview:
    """A flat byte view of a staged payload (array or bytes) — what the
    transports put on the wire, with no serialization copy."""
    if isinstance(payload, np.ndarray):
        # reshape(-1) first: numpy rejects dtype-changing views of 0-d
        # arrays (scalar leaves like an optax step count)
        return memoryview(payload.reshape(-1).view(np.uint8))
    return memoryview(payload)


def leaf_to_bytes(leaf: Any) -> bytes:
    if _is_array(leaf):
        return np.asarray(leaf).tobytes()
    return pickle.dumps(leaf)


def leaf_from_bytes(meta: TensorMeta, buf: Any) -> Any:
    """Rebuild a leaf from a received buffer (bytes, bytearray, or a uint8
    ndarray straight off a PG recv)."""
    if meta.kind == "pickled":
        return pickle.loads(bytes(buf))
    dtype = _np_dtype_from_str(meta.dtype)
    if isinstance(buf, np.ndarray):
        arr = buf.reshape(-1).view(np.uint8).view(dtype).reshape(meta.shape)
        return arr if buf.flags.owndata else arr.copy()
    arr = np.frombuffer(buf, dtype=dtype).reshape(meta.shape)
    # bytes may be a transient view (copy); a bytearray from a streamed
    # recv was allocated for this leaf and stays alive via arr.base
    return arr.copy() if isinstance(buf, bytes) else arr


def _np_dtype_from_str(name: str) -> np.dtype:
    from torchft_tpu.utils import np_dtype_from_str

    return np_dtype_from_str(name)


def alloc_leaf(meta: TensorMeta) -> np.ndarray:
    """Preallocate the final array for a streamed receive — the receiver
    reads the wire straight into this memory (readinto), so peak overhead
    stays O(stream buffer), not O(payload)."""
    return np.empty(meta.shape, _np_dtype_from_str(meta.dtype))


def unflatten_state(spec: TreeSpecPayload, payloads: Sequence[Any]) -> Any:
    import jax

    treedef = pickle.loads(spec.treedef_bytes)
    leaves = [
        # already-final leaves pass through: host ndarrays streamed into
        # place, and jax.Arrays an in-place template already device_put
        # (a multi-shard jax.Array doesn't even support the buffer
        # protocol leaf_from_bytes would use)
        b if (m.kind == "array"
              and isinstance(b, (np.ndarray, jax.Array)))
        else leaf_from_bytes(m, b)
        for m, b in zip(spec.leaves, payloads)
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def split_chunks(
    payload_sizes: Sequence[int], num_chunks: int
) -> List[List[int]]:
    """Greedy size-balanced assignment of leaf indices to chunks."""
    num_chunks = max(1, min(num_chunks, max(len(payload_sizes), 1)))
    chunks: List[List[int]] = [[] for _ in range(num_chunks)]
    sizes = [0] * num_chunks
    order = sorted(range(len(payload_sizes)), key=lambda i: -payload_sizes[i])
    for i in order:
        j = min(range(num_chunks), key=lambda k: sizes[k])
        chunks[j].append(i)
        sizes[j] += payload_sizes[i]
    return chunks


def can_absorb(template: Any, shape: Tuple[int, ...], dtype: Any,
               require_contiguous: bool = False) -> bool:
    """Whether a host ndarray ``template`` leaf can absorb an incoming
    leaf of ``shape``/``dtype`` in place. One predicate for every
    transport's in-place path so the absorb contract can't drift between
    them. ``require_contiguous`` is for direct socket-streaming receives,
    where ``reshape(-1)`` on a non-contiguous array would COPY and the
    stream would land in the copy, silently not in place."""
    if not isinstance(template, np.ndarray):
        return False
    if isinstance(dtype, str):
        dtype_ok = str(template.dtype) == dtype
    else:
        dtype_ok = template.dtype == np.dtype(dtype)
    return (
        template.shape == tuple(shape)
        and dtype_ok
        and template.flags.writeable
        and (not require_contiguous or template.flags["C_CONTIGUOUS"])
    )


def template_leaves_for(spec: TreeSpecPayload, template: Any,
                        logger: Any) -> Optional[List[Any]]:
    """Flatten ``template`` for index-aligned in-place placement, or
    return None (with one warning) when the SENDER's tree structure
    differs from the template's.

    The guard is load-bearing: in-place placement matches leaves purely
    by flat index, so a structural drift (e.g. the sender's model gained
    a layer mid-tree) with shape-coincident leaves would silently stream
    sender data into the WRONG live template buffers. Structure equality
    (treedef) makes index alignment sound; on mismatch the whole receive
    degrades to wire buffers — torn in-place state is worse than a slow
    heal."""
    import jax

    t_leaves, t_def = jax.tree_util.tree_flatten(template)
    # an undecodable treedef is not a degrade-and-continue case: the
    # receive would transfer the full checkpoint only to fail at
    # unflatten on the same exception — fail fast before moving bytes
    s_def = pickle.loads(spec.treedef_bytes)
    if s_def != t_def:
        # point at the first DIVERGING leaf path: the guard's canonical
        # case is shape-coincident KEY drift, where counts are equal and
        # truncated treedef reprs would print identical-looking prefixes
        def leaf_paths(treedef):
            dummy = jax.tree_util.tree_unflatten(
                treedef, list(range(treedef.num_leaves))
            )
            return [jax.tree_util.keystr(p) for p, _ in
                    jax.tree_util.tree_flatten_with_path(dummy)[0]]

        try:
            s_paths, t_paths = leaf_paths(s_def), leaf_paths(t_def)
            divergence = next(
                (f"first divergence at leaf {i}: sender {a!r} vs "
                 f"template {b!r}"
                 for i, (a, b) in enumerate(zip(s_paths, t_paths)) if a != b),
                f"trees agree on the first {min(len(s_paths), len(t_paths))}"
                f" leaves but have {len(s_paths)} vs {len(t_paths)}",
            )
        except Exception:  # noqa: BLE001 - diagnostics must not mask the guard
            divergence = f"sender {str(s_def)[:200]} vs template {str(t_def)[:200]}"
        logger.warning(
            "sender tree structure differs from the template's — "
            "index-aligned in-place placement would risk landing leaves "
            "in the wrong buffers; in-place receive degraded to wire "
            "buffers for this transfer (%s)",
            divergence,
        )
        return None
    return t_leaves


def place_leaf_like(host_leaf: np.ndarray, template: Any,
                    logger: Any) -> Any:
    """Land a received leaf where the template leaf lives (shared by the
    PG and HTTP transports' in-place receive paths).

    - jax.Array template: ``device_put`` to its sharding (the JAX analog of
      the reference's HBM-to-HBM in-place recv, pg_transport.py:235-305).
    - Host ndarray template: copy INTO the template's buffer and return it,
      so the wire buffer is freed per-leaf and repeated heals reuse one
      allocation — receiver peak stays ~template + one leaf instead of
      template + full checkpoint (measured at 12 GB in
      benchmarks/transport_bench.py --two-process --inplace).

    Never silently coerces: a template leaf that can't absorb (shape or
    dtype mismatch, unwritable) logs an "in-place receive degraded"
    warning on the caller's ``logger`` and the wire buffer is returned.
    """
    try:
        import jax

        if isinstance(template, jax.Array):
            if (template.dtype == host_leaf.dtype
                    and template.shape == host_leaf.shape):
                return jax.device_put(host_leaf, template.sharding)
            # same no-silent-coercion contract as the host path below: an
            # astype/reshape here would round, truncate, or reshard the
            # sender's values with no signal (shape and dtype can drift
            # when template and sender state were built from different
            # recipes, e.g. f32-master vs bf16) — fall through to the
            # degraded-warning path so the mismatch is visible in logs
        if can_absorb(template, host_leaf.shape, host_leaf.dtype):
            np.copyto(template, host_leaf)
            return template
        # a template that can't absorb the leaf silently costs the in-place
        # property (receiver RSS regresses from ~0.01x to ~1x payload over
        # repeated heals) — that degradation must be visible in logs
        logger.warning(
            "template leaf cannot absorb received leaf "
            "(template %s shape=%s dtype=%s writeable=%s vs received "
            "shape=%s dtype=%s); falling back to the wire buffer — "
            "in-place receive degraded",
            type(template).__name__,
            getattr(template, "shape", None),
            getattr(template, "dtype", None),
            getattr(getattr(template, "flags", None), "writeable", None),
            host_leaf.shape,
            host_leaf.dtype,
        )
    except Exception:  # noqa: BLE001 - fall back to the wire buffer
        logger.exception("failed to place leaf onto template")
    return host_leaf
