"""Pytree (de)serialization for checkpoint transports.

Role-equivalent of the reference's streaming torch.save/load
(torchft/checkpointing/_serialization.py:14-39) but for JAX pytrees: the tree
structure and per-leaf metadata travel as a pickled spec; array payloads are
raw little-endian buffers that can be split into chunks and fetched in
parallel (reference chunking: http_transport.py:287-298).
"""

from __future__ import annotations

import io
import pickle
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "TensorMeta",
    "TreeSpecPayload",
    "flatten_state",
    "unflatten_state",
    "leaf_to_bytes",
    "leaf_from_bytes",
    "split_chunks",
]


@dataclass
class TensorMeta:
    """Per-leaf metadata (reference: pg_transport.py:32-59 _TensorMeta).

    ``sharding`` optionally carries a jax.sharding description so the
    receiver can device_put straight back to the right layout.
    """

    dtype: str
    shape: Tuple[int, ...]
    nbytes: int
    kind: str = "array"  # "array" | "pickled" (non-array leaf)


@dataclass
class TreeSpecPayload:
    """Pickled header: tree structure + leaf metadata."""

    treedef_bytes: bytes
    leaves: List[TensorMeta] = field(default_factory=list)


def _is_array(x: Any) -> bool:
    return isinstance(x, np.ndarray) or type(x).__module__.startswith("jax")


def flatten_state(state: Any) -> Tuple[TreeSpecPayload, List[bytes]]:
    """Flatten a pytree into (spec, per-leaf payloads).

    Array leaves (numpy or jax) are staged to host and serialized as raw
    buffers; other leaves are pickled.
    """
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(state)
    metas: List[TensorMeta] = []
    payloads: List[bytes] = []
    for leaf in leaves:
        if _is_array(leaf):
            host = np.asarray(leaf)
            buf = host.tobytes()
            metas.append(
                TensorMeta(
                    dtype=str(host.dtype), shape=tuple(host.shape), nbytes=len(buf)
                )
            )
            payloads.append(buf)
        else:
            buf = pickle.dumps(leaf)
            metas.append(
                TensorMeta(dtype="", shape=(), nbytes=len(buf), kind="pickled")
            )
            payloads.append(buf)
    spec = TreeSpecPayload(treedef_bytes=pickle.dumps(treedef), leaves=metas)
    return spec, payloads


def leaf_to_bytes(leaf: Any) -> bytes:
    if _is_array(leaf):
        return np.asarray(leaf).tobytes()
    return pickle.dumps(leaf)


def leaf_from_bytes(meta: TensorMeta, buf: bytes) -> Any:
    if meta.kind == "pickled":
        return pickle.loads(buf)
    arr = np.frombuffer(buf, dtype=np.dtype(meta.dtype)).reshape(meta.shape)
    return arr.copy()  # own the memory (buf may be a transient view)


def unflatten_state(spec: TreeSpecPayload, payloads: Sequence[bytes]) -> Any:
    import jax

    treedef = pickle.loads(spec.treedef_bytes)
    leaves = [leaf_from_bytes(m, b) for m, b in zip(spec.leaves, payloads)]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def split_chunks(
    payload_sizes: Sequence[int], num_chunks: int
) -> List[List[int]]:
    """Greedy size-balanced assignment of leaf indices to chunks."""
    num_chunks = max(1, min(num_chunks, max(len(payload_sizes), 1)))
    chunks: List[List[int]] = [[] for _ in range(num_chunks)]
    sizes = [0] * num_chunks
    order = sorted(range(len(payload_sizes)), key=lambda i: -payload_sizes[i])
    for i in order:
        j = min(range(num_chunks), key=lambda k: sizes[k])
        chunks[j].append(i)
        sizes[j] += payload_sizes[i]
    return chunks
