"""Pytree (de)serialization for checkpoint transports.

Role-equivalent of the reference's streaming torch.save/load
(torchft/checkpointing/_serialization.py:14-39) but for JAX pytrees: the tree
structure and per-leaf metadata travel as a pickled spec; array payloads are
raw little-endian buffers that can be split into chunks and fetched in
parallel (reference chunking: http_transport.py:287-298).
"""

from __future__ import annotations

import io
import pickle
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "TensorMeta",
    "TreeSpecPayload",
    "alloc_leaf",
    "flatten_state",
    "unflatten_state",
    "leaf_to_bytes",
    "leaf_from_bytes",
    "payload_memoryview",
    "split_chunks",
]


@dataclass
class TensorMeta:
    """Per-leaf metadata (reference: pg_transport.py:32-59 _TensorMeta).

    Layout restore is the receiver's job: transports place received leaves
    onto a caller-provided template's sharding (PGTransport's in-place
    receive) rather than shipping sharding descriptions on the wire.
    """

    dtype: str
    shape: Tuple[int, ...]
    nbytes: int
    kind: str = "array"  # "array" | "pickled" (non-array leaf)


@dataclass
class TreeSpecPayload:
    """Pickled header: tree structure + leaf metadata."""

    treedef_bytes: bytes
    leaves: List[TensorMeta] = field(default_factory=list)


def _is_array(x: Any) -> bool:
    return isinstance(x, np.ndarray) or type(x).__module__.startswith("jax")


def flatten_state(state: Any) -> Tuple[TreeSpecPayload, List[Any]]:
    """Flatten a pytree into (spec, per-leaf payloads).

    Array leaves (numpy or jax) are staged to host and kept as **arrays**
    (a zero-copy view for numpy inputs; one D2H for jax) — NOT serialized
    to bytes here. Transports stream straight from the array memory, so
    peak host memory stays ~1x the payload instead of the 2-3x that
    pre-serializing every leaf costs (VERDICT round-2 item 6). Non-array
    leaves are pickled bytes.
    """
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(state)
    metas: List[TensorMeta] = []
    payloads: List[Any] = []
    for leaf in leaves:
        if _is_array(leaf):
            if isinstance(leaf, np.ndarray):
                # snapshot: a live numpy leaf may be mutated in place by the
                # training loop while the serving window is open — streaming
                # an alias would tear the checkpoint mid-leaf
                host = np.array(leaf, copy=True, order="C")
            else:
                # jax.Array: on accelerators np.asarray materializes a
                # fresh host buffer (one D2H). On the CPU backend it can
                # be a ZERO-COPY alias of the live device buffer, which a
                # later donated step may reuse while the serving window is
                # still streaming — so force ownership when aliased.
                host = np.ascontiguousarray(np.asarray(leaf))
                if host.base is not None or not host.flags.owndata:
                    host = host.copy()
            metas.append(
                TensorMeta(
                    dtype=str(host.dtype),
                    shape=tuple(host.shape),
                    nbytes=host.nbytes,
                )
            )
            payloads.append(host)
        else:
            buf = pickle.dumps(leaf)
            metas.append(
                TensorMeta(dtype="", shape=(), nbytes=len(buf), kind="pickled")
            )
            payloads.append(buf)
    spec = TreeSpecPayload(treedef_bytes=pickle.dumps(treedef), leaves=metas)
    return spec, payloads


def payload_memoryview(payload: Any) -> memoryview:
    """A flat byte view of a staged payload (array or bytes) — what the
    transports put on the wire, with no serialization copy."""
    if isinstance(payload, np.ndarray):
        # reshape(-1) first: numpy rejects dtype-changing views of 0-d
        # arrays (scalar leaves like an optax step count)
        return memoryview(payload.reshape(-1).view(np.uint8))
    return memoryview(payload)


def leaf_to_bytes(leaf: Any) -> bytes:
    if _is_array(leaf):
        return np.asarray(leaf).tobytes()
    return pickle.dumps(leaf)


def leaf_from_bytes(meta: TensorMeta, buf: Any) -> Any:
    """Rebuild a leaf from a received buffer (bytes, bytearray, or a uint8
    ndarray straight off a PG recv)."""
    if meta.kind == "pickled":
        return pickle.loads(bytes(buf))
    dtype = _np_dtype_from_str(meta.dtype)
    if isinstance(buf, np.ndarray):
        arr = buf.reshape(-1).view(np.uint8).view(dtype).reshape(meta.shape)
        return arr if buf.flags.owndata else arr.copy()
    arr = np.frombuffer(buf, dtype=dtype).reshape(meta.shape)
    # bytes may be a transient view (copy); a bytearray from a streamed
    # recv was allocated for this leaf and stays alive via arr.base
    return arr.copy() if isinstance(buf, bytes) else arr


def _np_dtype_from_str(name: str) -> np.dtype:
    from torchft_tpu.utils import np_dtype_from_str

    return np_dtype_from_str(name)


def alloc_leaf(meta: TensorMeta) -> np.ndarray:
    """Preallocate the final array for a streamed receive — the receiver
    reads the wire straight into this memory (readinto), so peak overhead
    stays O(stream buffer), not O(payload)."""
    return np.empty(meta.shape, _np_dtype_from_str(meta.dtype))


def unflatten_state(spec: TreeSpecPayload, payloads: Sequence[Any]) -> Any:
    import jax

    treedef = pickle.loads(spec.treedef_bytes)
    leaves = [
        b if (isinstance(b, np.ndarray) and m.kind == "array")
        else leaf_from_bytes(m, b)
        for m, b in zip(spec.leaves, payloads)
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def split_chunks(
    payload_sizes: Sequence[int], num_chunks: int
) -> List[List[int]]:
    """Greedy size-balanced assignment of leaf indices to chunks."""
    num_chunks = max(1, min(num_chunks, max(len(payload_sizes), 1)))
    chunks: List[List[int]] = [[] for _ in range(num_chunks)]
    sizes = [0] * num_chunks
    order = sorted(range(len(payload_sizes)), key=lambda i: -payload_sizes[i])
    for i in order:
        j = min(range(num_chunks), key=lambda k: sizes[k])
        chunks[j].append(i)
        sizes[j] += payload_sizes[i]
    return chunks
