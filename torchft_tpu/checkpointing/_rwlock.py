"""Timed readers-writer lock.

Guards live-checkpoint state reads against concurrent optimizer mutation,
as in the reference (torchft/checkpointing/_rwlock.py:47-136; used by
manager.py:243 and local_sgd.py:111-123). Read-preferring, matching the
reference contract: overlapping/nested read acquisitions succeed even while
a writer is waiting (checkpoint-send holds the read lock while state-dict
callbacks re-enter it).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Generator

__all__ = ["RWLock"]


class RWLock:
    def __init__(self, timeout: float = -1) -> None:
        """``timeout``: default acquire timeout in seconds (-1 = forever)."""
        self._timeout = timeout
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False

    # -- read side --------------------------------------------------------
    def r_acquire(self, timeout: float | None = None) -> bool:
        timeout = self._timeout if timeout is None else timeout
        with self._cond:
            ok = self._cond.wait_for(
                lambda: not self._writer,
                timeout=None if timeout < 0 else timeout,
            )
            if not ok:
                return False
            self._readers += 1
            return True

    def r_release(self) -> None:
        with self._cond:
            assert self._readers > 0, "r_release without matching r_acquire"
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    @contextmanager
    def r_lock(self, timeout: float | None = None) -> Generator[None, None, None]:
        if not self.r_acquire(timeout=timeout):
            raise TimeoutError("timed out acquiring read lock")
        try:
            yield
        finally:
            self.r_release()

    # -- write side -------------------------------------------------------
    def w_acquire(self, timeout: float | None = None) -> bool:
        timeout = self._timeout if timeout is None else timeout
        with self._cond:
            ok = self._cond.wait_for(
                lambda: not self._writer and self._readers == 0,
                timeout=None if timeout < 0 else timeout,
            )
            if not ok:
                return False
            self._writer = True
            return True

    def w_release(self) -> None:
        with self._cond:
            assert self._writer, "w_release without matching w_acquire"
            self._writer = False
            self._cond.notify_all()

    @contextmanager
    def w_lock(self, timeout: float | None = None) -> Generator[None, None, None]:
        if not self.w_acquire(timeout=timeout):
            raise TimeoutError("timed out acquiring write lock")
        try:
            yield
        finally:
            self.w_release()

    # -- introspection ----------------------------------------------------
    def r_locked(self) -> bool:
        with self._cond:
            return self._readers > 0

    def w_locked(self) -> bool:
        with self._cond:
            return self._writer
