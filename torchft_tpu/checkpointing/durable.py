"""Durable (tier-2) checkpointing for fault-tolerant training.

The framework's checkpoint story is two-tier (reference: torchft
manager.py:148-160 note + train_ddp.py:197-204 comments):

- **tier 1, live recovery** — `CheckpointTransport` heals a rejoining
  replica from a healthy peer's memory. Fast, but requires at least one
  live replica: a whole-job outage (pod preemption, maintenance) loses
  everything.
- **tier 2, durable checkpoints** — periodic snapshots to persistent
  storage. The reference leaves this entirely to the user ("must include
  Manager.state_dict()"); here it is a first-class helper so the contract
  can't be gotten wrong.

TPU-first: persistence is delegated to orbax (the JAX-native checkpoint
library — async array serialization, atomic step directories, retention),
with the framework contributing the *composition*: user state + the
Manager's quorum clock + the data iterator position are saved and restored
as one atomic step so a cold-started job resumes exactly where the fleet
died.

Usage::

    ckpt = DurableCheckpointer(dir, max_to_keep=3, save_interval_steps=100)
    restored = ckpt.restore(state_template=state)
    if restored is not None:
        state, manager_sd, data_sd = restored
        manager.load_state_dict(manager_sd)
        data_iter.load_state_dict(data_sd)
    ...
    ckpt.maybe_save(manager.current_step(), state,
                    manager=manager, data_iter=data_iter)
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional, Tuple

logger = logging.getLogger(__name__)

__all__ = ["DurableCheckpointer"]


class DurableCheckpointer:
    """Periodic durable checkpoints of (user state, manager clock, data
    position) with retention, backed by orbax.

    Each replica group checkpoints independently (pass a per-replica
    ``directory``); on cold start every group restores its own latest step
    and the first quorum reconciles stragglers via tier-1 live healing.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        save_interval_steps: int = 0,
    ) -> None:
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._directory = os.path.abspath(directory)
        os.makedirs(self._directory, exist_ok=True)
        self._interval = save_interval_steps
        self._manager = ocp.CheckpointManager(
            self._directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                create=True,
            ),
        )

    # ------------------------------------------------------------------ save
    def maybe_save(
        self,
        step: int,
        state: Any,
        manager: Any = None,
        data_iter: Any = None,
    ) -> bool:
        """Save iff ``step`` is on the configured interval (and is new).
        Returns whether a save was issued.

        ``state`` may be a zero-arg callable; it is invoked only when a
        save actually happens, so composing an expensive composite (e.g.
        ``manager.user_state_dict``, which takes the state-dict read lock)
        costs nothing on the ~interval-1 steps that skip."""
        if step <= 0:  # never burn a retention slot on untrained init state
            return False
        if self._interval <= 0 or step % self._interval != 0:
            return False
        if self._manager.latest_step() == step:
            return False
        if callable(state):
            state = state()
        return self.save(step, state, manager=manager, data_iter=data_iter)

    def save(
        self,
        step: int,
        state: Any,
        manager: Any = None,
        data_iter: Any = None,
        force: bool = False,
    ) -> bool:
        """Snapshot user state (a pytree of arrays) plus the manager's
        step/commit counters and the data iterator position. Array writes
        are async (orbax) — call ``wait()`` before process exit."""
        ocp = self._ocp
        items = {"state": ocp.args.StandardSave(state)}
        if manager is not None:
            items["torchft"] = ocp.args.JsonSave(manager.state_dict())
        if data_iter is not None:
            items["data"] = ocp.args.JsonSave(data_iter.state_dict())
        saved = self._manager.save(
            step, args=ocp.args.Composite(**items), force=force
        )
        if saved:
            logger.info(f"durable checkpoint saved at step {step}")
        return bool(saved)

    # --------------------------------------------------------------- restore
    def restore(
        self, state_template: Any = None, step: Optional[int] = None
    ) -> Optional[Tuple[Any, Optional[dict], Optional[dict]]]:
        """Restore ``(state, manager_state_dict, data_state_dict)`` from
        ``step`` (default: latest). Returns None when no checkpoint exists.

        ``state_template`` (a matching pytree, e.g. the freshly initialized
        state) restores arrays with the template's dtypes/shardings — on TPU
        this places leaves straight back onto their devices.
        """
        ocp = self._ocp
        if step is None:
            step = self._manager.latest_step()
        if step is None:
            return None
        targets = {
            "state": ocp.args.StandardRestore(state_template)
            if state_template is not None
            else ocp.args.StandardRestore()
        }
        saved_items = set(self._manager.item_metadata(step).keys())
        if "torchft" in saved_items:
            targets["torchft"] = ocp.args.JsonRestore()
        if "data" in saved_items:
            targets["data"] = ocp.args.JsonRestore()
        out = self._manager.restore(step, args=ocp.args.Composite(**targets))
        return (
            out["state"],
            out.get("torchft"),
            out.get("data"),
        )

    # ------------------------------------------------------------- lifecycle
    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def all_steps(self) -> list:
        return sorted(self._manager.all_steps())

    def wait(self) -> None:
        """Block until in-flight async array writes are durable."""
        self._manager.wait_until_finished()

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()
