"""Checkpoint transport over ProcessGroup point-to-point sends.

Design mirror of the reference PGTransport
(torchft/checkpointing/pg_transport.py:168-305): a pickled spec (tree
structure + per-leaf metadata) followed by raw per-leaf buffers, sent via a
*second* process group dedicated to recovery so healing traffic never
interleaves with training collectives. Supports in-place receive into an
existing state pytree: leaves are rebuilt with the template's dtype/sharding
(``jax.device_put`` to the template leaf's sharding), the JAX analog of the
reference's HBM-to-HBM in-place recv (pg_transport.py:235-305).
"""

from __future__ import annotations

import logging
import pickle
import zlib
from datetime import timedelta
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from torchft_tpu.checkpointing._serialization import (
    TensorMeta,
    TreeSpecPayload,
    can_absorb,
    flatten_state,
    leaf_from_bytes,
    place_leaf_like,
    template_leaves_for,
)
from torchft_tpu.checkpointing.transport import (
    CheckpointTransport,
    StreamTimings,
    pipelined,
    plan_wire_ranges,
    stream_chunk_bytes,
)
from torchft_tpu.process_group import ProcessGroup

logger = logging.getLogger(__name__)

__all__ = ["PGTransport"]


def _chunk_crc(wires: List[np.ndarray], chunk: List[Tuple[int, int, int]]) -> int:
    """crc32 over a chunk's concatenated range payloads, in plan order."""
    crc = 0
    for j, off, ln in chunk:
        crc = zlib.crc32(wires[j][off : off + ln], crc)
    return crc & 0xFFFFFFFF


class PGTransport(CheckpointTransport[Any]):
    """Send checkpoints over PG send/recv.

    ``state_dict_template`` (optional callable returning a pytree) enables
    in-place receive: received leaves are placed onto the same device/sharding
    as the template's leaves; host ndarray template leaves are written
    in place (``np.copyto``) so repeated heals reuse one allocation.

    In-place contract (same as the reference's HBM in-place recv,
    pg_transport.py:235-305): leaves land in the template AS THEY ARRIVE,
    so a mid-stream failure (sender died, timeout) raises with the template
    torn between old and new state. That is safe exactly when a failed heal
    is never committed and is retried before the state is used — which the
    Manager protocol guarantees (a recv_checkpoint exception reaches
    ``report_error``, the step is discarded at should_commit, and the next
    quorum heals again). Callers outside the Manager who hand their live
    state as the template must either provide the same guarantee or pass a
    scratch template.
    """

    def __init__(
        self,
        pg: ProcessGroup,
        timeout: "float | timedelta" = 60.0,
        state_dict_template: Optional[Callable[[], Any]] = None,
        snapshot_send: bool = True,
    ) -> None:
        """``snapshot_send=False`` streams straight from the caller's
        arrays (no per-heal checkpoint copy). Safe only when nothing
        mutates registered numpy state while send_checkpoint runs — true
        under a sync-quorum Manager (the trainer is blocked inside
        start_quorum during the heal) or when all mutable state is
        jax.Arrays (immutable buffers; functional updates rebind instead
        of writing in place). An async-quorum host-plane trainer that
        mutates numpy state in place (EMA buffers, running stats) must
        keep the default or a heal can read a torn leaf."""
        self._pg = pg
        self._snapshot_send = snapshot_send
        self._timeout = (
            timeout.total_seconds() if isinstance(timeout, timedelta) else timeout
        )
        if state_dict_template is not None and not callable(state_dict_template):
            # fail at construction, not on the first heal (where the
            # TypeError would surface as an endlessly-retried heal error)
            raise TypeError(
                "state_dict_template must be a zero-arg callable returning "
                "the template pytree, not the pytree itself "
                f"(got {type(state_dict_template).__name__})"
            )
        self._template_fn = state_dict_template

    def metadata(self) -> str:
        return "<pg_transport>"

    def configure(
        self,
        store_addr: str,
        replica_rank: int,
        replica_world_size: int,
        quorum_id: int = 0,
    ) -> None:
        """Rendezvous the recovery PG with the current quorum (called by
        the Manager after its own PG reconfigure; see
        CheckpointTransport.configure). The recovery PG must be a separate
        instance from the Manager's — the host plane rejects mixing p2p
        and collective traffic on one generation."""
        self._pg.configure(
            store_addr, replica_rank, replica_world_size, quorum_id=quorum_id
        )

    SEND_WINDOW = 4
    # Batched-wire message cap: bounds how much one tag-2 message can
    # buffer in a ProcessGroupBaby child (which pickles whole messages
    # through its pipe) while still amortizing per-message control
    # round-trips ~leaves-per-group times. Both sides derive the SAME
    # grouping from the spec, so the protocol needs no extra negotiation.
    BATCH_GROUP_BYTES = 256 << 20

    @classmethod
    def _wire_groups(cls, spec) -> List[List[int]]:
        """Deterministic partition of leaf indices into wire messages:
        consecutive leaves packed up to BATCH_GROUP_BYTES per message
        (always at least one leaf). Derived identically by sender and
        receiver from the spec that rides the header."""
        groups: List[List[int]] = []
        cur: List[int] = []
        cur_bytes = 0
        for i, meta in enumerate(spec.leaves):
            if cur and cur_bytes + meta.nbytes > cls.BATCH_GROUP_BYTES:
                groups.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += meta.nbytes
        if cur:
            groups.append(cur)
        return groups

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: Any, timeout
    ) -> None:
        # snapshot_send=False streams straight from the caller's arrays
        # (see __init__); the default copies numpy leaves so a training
        # loop mutating them mid-stream cannot tear the checkpoint
        spec, payloads = flatten_state(
            state_dict, snapshot=self._snapshot_send
        )
        # Ranged wire when the PG streams raw frames (direct
        # ProcessGroupHost — recv_into is the capability marker): each
        # message carries a chunk of BYTE RANGES (leaf_idx, offset, nbytes)
        # planned by plan_wire_ranges, so a single multi-GB leaf splits
        # across messages and the receiver overlaps the recv of chunk i+1
        # with the device placement of chunk i (pipelined heal). The plan
        # rides the header — no cross-host determinism requirement on the
        # chunk-size knob. The header tells the receiver which protocol is
        # on the wire; the non-ranged header stays a 2-tuple for pre-split
        # receivers, and the legacy batched protocol is still understood
        # on receive for mixed-version heals.
        ranged = hasattr(self._pg, "recv_into")
        ranges: Optional[List[Any]] = None
        wires = [
            buf.reshape(-1).view(np.uint8)
            if isinstance(buf, np.ndarray)
            else np.frombuffer(buf, dtype=np.uint8)
            for buf in payloads
        ]
        if ranged:
            chunk_bytes = min(self.BATCH_GROUP_BYTES, stream_chunk_bytes())
            ranges = plan_wire_ranges(
                [m.nbytes for m in spec.leaves], chunk_bytes
            )
            # per-chunk crc32 over the concatenated range payloads rides the
            # header as a 5th element: pre-crc receivers unpack tolerantly
            # and skip verification, pre-crc senders ship a 4-tuple and the
            # receiver sees crcs=None — both directions interop
            crcs = [
                _chunk_crc(wires, chunk) for chunk in ranges
            ]
            header = pickle.dumps((step, spec, "ranged", ranges, crcs))
        else:
            header = pickle.dumps((step, spec))
        for dst in dst_ranks:
            self._pg.send([np.frombuffer(header, dtype=np.uint8)], dst, tag=1).wait(
                self._timeout
            )
            if ranged:
                assert ranges is not None
                # windowed like the per-leaf path: bounds in-flight chunk
                # copies on a buffering peer while keeping the wire busy
                pending: List[Any] = []
                for chunk in ranges:
                    bufs = [wires[j][off : off + ln] for (j, off, ln) in chunk]
                    pending.append(self._pg.send(bufs, dst, tag=2))
                    if len(pending) >= self.SEND_WINDOW:
                        pending.pop(0).wait(self._timeout)
                for work in pending:
                    work.wait(self._timeout)
                continue
            # Windowed per-leaf sends: keep at most SEND_WINDOW leaves in
            # flight. The window is not about caller overlap — it is
            # BACKPRESSURE: with a ProcessGroupBaby recovery PG each
            # in-flight send is a pickled full-leaf copy buffered in the
            # child process, and an unbounded issue loop would materialize
            # a checkpoint-sized pile of copies there (12GB-class state
            # dicts → host OOM during healing). The reference's per-leaf
            # blocking wait (pg_transport.py:202-233) is the window=1
            # special case.
            pending: List[Any] = []
            for wire in wires:
                pending.append(self._pg.send([wire], dst, tag=2))
                if len(pending) >= self.SEND_WINDOW:
                    pending.pop(0).wait(self._timeout)
            for work in pending:
                work.wait(self._timeout)

    def recv_checkpoint(self, src_rank: int, metadata: str, step: int, timeout) -> Any:
        timeout_s = (
            timeout.total_seconds() if isinstance(timeout, timedelta) else timeout
        )
        header = self._pg.recv(src_rank, tag=1).get_future().wait(timeout_s)
        # tolerant unpack: a pre-batching peer sends (step, spec), a
        # batching peer (step, spec, True), a ranged peer
        # (step, spec, "ranged", ranges) — mixed-version heals still work
        got_step, spec, *rest = pickle.loads(bytes(header[0]))
        proto = rest[0] if rest else False
        if got_step != step:
            raise RuntimeError(f"expected checkpoint step {step}, got {got_step}")

        template_leaves: Optional[List[Any]] = None
        if self._template_fn is not None:
            # returns None (one warning) when the sender's tree STRUCTURE
            # differs from the template's — index-aligned placement would
            # risk streaming leaves into the wrong buffers
            template_leaves = template_leaves_for(
                spec, self._template_fn(), logger
            )

        # direct-into-template receive (feature-detected: beyond the torch
        # PG surface; Baby PGs fall back to the recv+place path): a host
        # template leaf that can absorb gets the raw frame streamed into
        # its own memory — no wire allocation, no copy
        recv_into = getattr(self._pg, "recv_into", None)

        def _absorb_target(i: int, meta) -> Optional[np.ndarray]:
            if (
                recv_into is not None
                and template_leaves is not None
                and meta.kind == "array"
                and can_absorb(template_leaves[i], meta.shape, meta.dtype,
                               require_contiguous=True)
            ):
                return template_leaves[i]
            return None

        def _finish_leaf(i: int, meta, wire_buf) -> Any:
            # pass the received ndarray straight through: leaf_from_bytes's
            # ndarray path re-views it with zero copies (bytes() would cost
            # two extra full-leaf copies)
            leaf = leaf_from_bytes(meta, wire_buf)
            if template_leaves is not None and meta.kind == "array":
                leaf = place_leaf_like(leaf, template_leaves[i], logger)
            return leaf

        payload_leaves: List[Any] = []
        if proto == "ranged":
            return self._recv_ranged(
                src_rank, spec, rest[1], template_leaves, timeout_s,
                crcs=rest[2] if len(rest) > 2 else None,
            )
        if proto:
            # one message per wire group (same deterministic grouping as
            # the sender derives from this spec). Absorb-capable template
            # leaves ride as preallocated views so their raw frames stream
            # straight into the template's memory; the rest land in wire
            # buffers and are placed after.
            targets = [_absorb_target(i, m) for i, m in enumerate(spec.leaves)]
            views = [
                t.reshape(-1).view(np.uint8) if t is not None else None
                for t in targets
            ]
            for group in self._wire_groups(spec):
                gviews = [views[i] for i in group]
                if recv_into is not None:
                    got = self._pg.recv_into(gviews, src_rank, tag=2) \
                        .get_future().wait(timeout_s)
                else:
                    got = self._pg.recv(src_rank, tag=2).get_future().wait(
                        timeout_s
                    )
                n_got = len(got) if got else 0
                if n_got != len(group):
                    err = self._pg.errored()
                    raise RuntimeError(
                        f"batched recv from rank {src_rank} returned "
                        f"{n_got} of {len(group)} leaves (pg errored: "
                        f"{err})"
                    )
                for j, i in enumerate(group):
                    meta = spec.leaves[i]
                    if views[i] is not None and got[j] is views[i]:
                        payload_leaves.append(targets[i])
                    else:
                        payload_leaves.append(_finish_leaf(i, meta, got[j]))
        else:
            for i, meta in enumerate(spec.leaves):
                target = _absorb_target(i, meta)
                if target is not None:
                    # the wire carries the leaf as one flat uint8 frame;
                    # hand recv_into the template's flat view so the frame
                    # lands in the template's buffer (identity of the
                    # returned entry is the absorbed/fallback signal)
                    view = target.reshape(-1).view(np.uint8)
                    got = self._pg.recv_into([view], src_rank, tag=2) \
                        .get_future().wait(timeout_s)
                    if got and got[0] is view:
                        payload_leaves.append(target)
                        continue
                    buf = got  # pickled path or wire/buffer mismatch
                else:
                    buf = self._pg.recv(src_rank, tag=2).get_future().wait(
                        timeout_s
                    )
                if not buf:
                    # an aborted/errored receive resolves to an empty
                    # result; indexing it would mask the transport failure
                    # with an IndexError
                    err = self._pg.errored()
                    raise RuntimeError(
                        f"recv of leaf {i} from rank {src_rank} returned no "
                        f"buffer (pg errored: {err})"
                    )
                payload_leaves.append(_finish_leaf(i, meta, buf[0]))

        import jax

        treedef = pickle.loads(spec.treedef_bytes)
        return jax.tree_util.tree_unflatten(treedef, payload_leaves)

    def _recv_ranged(
        self,
        src_rank: int,
        spec: TreeSpecPayload,
        ranges: List[List[Any]],
        template_leaves: Optional[List[Any]],
        timeout_s: float,
        crcs: Optional[List[int]] = None,
    ) -> Any:
        """Receive the ranged wire: one message per chunk of byte ranges
        (the plan rode the header). The recv of chunk i+1 runs on a worker
        thread while this thread finalizes (device-places) the leaves
        chunk i completed — the pipelining that hides placement behind the
        wire for multi-chunk heals.

        ``crcs`` (when the sender's header carries them) are verified per
        chunk after the copy into the destination views — detection only on
        this push-based wire: a mismatch raises, the Manager's
        discard-and-retry heal protocol re-requests the transfer, and the
        corrupt bytes are never finalized into leaves."""
        recv_into = getattr(self._pg, "recv_into", None)

        # flat uint8 destination per leaf: absorb-capable template leaves
        # expose their own memory (frames stream straight in), the rest
        # get a wire buffer reused across that leaf's ranges
        dests: List[np.ndarray] = []
        absorbed: List[bool] = []
        for i, meta in enumerate(spec.leaves):
            target = None
            if (
                recv_into is not None
                and template_leaves is not None
                and meta.kind == "array"
                and can_absorb(
                    template_leaves[i],
                    meta.shape,
                    meta.dtype,
                    require_contiguous=True,
                )
            ):
                target = template_leaves[i]
            if target is not None:
                dests.append(target.reshape(-1).view(np.uint8))
                absorbed.append(True)
            else:
                dests.append(np.empty(meta.nbytes, np.uint8))
                absorbed.append(False)

        payloads: List[Optional[Any]] = [None] * len(spec.leaves)
        remaining: List[int] = [m.nbytes for m in spec.leaves]

        def _finalize(i: int) -> None:
            meta = spec.leaves[i]
            if absorbed[i]:
                assert template_leaves is not None
                payloads[i] = template_leaves[i]
                return
            leaf = leaf_from_bytes(meta, dests[i])
            if template_leaves is not None and meta.kind == "array":
                leaf = place_leaf_like(leaf, template_leaves[i], logger)
            payloads[i] = leaf

        def transfer(item: Any) -> List[Any]:
            ci, chunk = item
            gviews = [dests[j][off : off + ln] for (j, off, ln) in chunk]
            if recv_into is not None:
                got = self._pg.recv_into(gviews, src_rank, tag=2) \
                    .get_future().wait(timeout_s)
            else:
                got = self._pg.recv(src_rank, tag=2).get_future().wait(
                    timeout_s
                )
            n_got = len(got) if got else 0
            if n_got != len(chunk):
                err = self._pg.errored()
                raise RuntimeError(
                    f"ranged recv from rank {src_rank} returned {n_got} of "
                    f"{len(chunk)} ranges (pg errored: {err})"
                )
            for k, (j, _off, ln) in enumerate(chunk):
                if got[k] is gviews[k]:
                    continue  # absorbed straight into the destination
                src = got[k]
                buf = (
                    src.reshape(-1).view(np.uint8)
                    if isinstance(src, np.ndarray)
                    else np.frombuffer(src, np.uint8)
                )
                if buf.size != ln:
                    raise RuntimeError(
                        f"ranged recv: range {k} of chunk carries "
                        f"{buf.size} bytes, plan says {ln}"
                    )
                np.copyto(gviews[k], buf)
            if crcs is not None:
                got_crc = 0
                for gv in gviews:
                    got_crc = zlib.crc32(gv, got_crc)
                if got_crc & 0xFFFFFFFF != crcs[ci] & 0xFFFFFFFF:
                    raise RuntimeError(
                        f"ranged recv: chunk {ci} crc32 mismatch "
                        f"(got {got_crc & 0xFFFFFFFF:#010x}, header says "
                        f"{crcs[ci] & 0xFFFFFFFF:#010x}); discarding heal"
                    )
            return chunk

        def finish(chunk: List[Any]) -> None:
            for j, _off, ln in chunk:
                remaining[j] -= ln
                if remaining[j] < 0:
                    raise RuntimeError(
                        f"leaf {j}: overlapping/duplicate wire ranges"
                    )
                if remaining[j] == 0 and payloads[j] is None:
                    _finalize(j)

        timings = StreamTimings()
        pipelined(
            list(enumerate(ranges)),
            transfer,
            finish,
            depth=2,
            timings=timings,
            size_of=lambda c: sum(ln for (_j, _o, ln) in c),
        )
        self._last_recv_timings = timings

        missing = [i for i, p in enumerate(payloads) if p is None]
        if missing:
            raise RuntimeError(f"ranged checkpoint missing leaves {missing}")

        import jax

        treedef = pickle.loads(spec.treedef_bytes)
        return jax.tree_util.tree_unflatten(treedef, payloads)

    def shutdown(self, wait: bool = True) -> None:
        pass  # the PG is owned by the caller


