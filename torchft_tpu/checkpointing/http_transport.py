"""HTTP checkpoint transport: the default live-recovery path.

Design mirror of the reference HTTPTransport
(torchft/checkpointing/http_transport.py:38-266): a threaded HTTP server
serving ``/checkpoint/{step}/{metadata|chunk_{i}}``, gated by an RWLock so
serving can be disallowed while the optimizer mutates state; receivers fetch
chunks in parallel and reassemble the pytree.

Both directions stream (reference `_streaming_save/_load`,
http_transport.py:219-266): the sender serves leaf payloads straight from
the staged host arrays — one [leaf_idx, offset, nbytes] frame header then
the raw byte range, no pre-pickled chunk bodies — and the receiver reads
each frame directly into the leaf's final preallocated array
(``readinto``). Peak host overhead is O(stream buffer), not O(payload),
which is what makes 12GB-class state dicts transferable at 8B scale.

Wire chunks are BYTE ranges (``plan_wire_ranges``), not whole leaves: a
single multi-GB fused parameter buffer splits across chunks, so parallel
chunk fetches overlap its network transfer with the device placement of
already-complete leaves instead of store-and-forwarding one blob. Wire
version 2; v1 senders (whole-leaf ``[leaf_idx, nbytes]`` frames) are still
understood on receive.
"""

from __future__ import annotations

import logging
import pickle
import socket
import struct
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, List, Optional

from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.checkpointing._serialization import (
    TreeSpecPayload,
    alloc_leaf,
    can_absorb,
    flatten_state,
    payload_memoryview,
    place_leaf_like,
    template_leaves_for,
    unflatten_state,
)
from torchft_tpu.checkpointing.transport import (
    CheckpointTransport,
    ChunkStat,
    StreamTimings,
    plan_wire_ranges,
    stream_chunk_bytes,
)

logger = logging.getLogger(__name__)

__all__ = ["HTTPTransport"]

_FRAME = struct.Struct("<qq")  # v1: leaf_idx, nbytes (whole leaf)
_FRAME_V2 = struct.Struct("<qqq")  # leaf_idx, offset, nbytes (byte range)
_WIRE_VERSION = 2
# cap on auto-planned chunks (num_chunks=0): bounds fetch parallelism and
# the per-chunk frame overhead on huge states
_AUTO_MAX_CHUNKS = 8


def _to_seconds(timeout: "float | timedelta") -> float:
    return timeout.total_seconds() if isinstance(timeout, timedelta) else float(timeout)


class HTTPTransport(CheckpointTransport[Any]):
    """Serve checkpoints over HTTP; receive with parallel chunk fetch.

    ``num_chunks=0`` auto-plans byte-range chunks of roughly
    ``TORCHFT_STREAM_CHUNK_BYTES`` (default 32 MiB, at most 8 chunks), so
    the default transport pipelines large heals; ``num_chunks>0`` forces
    that many chunks. Chunk boundaries are byte offsets, not leaf
    boundaries — one huge leaf still streams as multiple chunks.

    ``state_dict_template`` (zero-arg callable returning a pytree, same
    contract as PGTransport's) enables in-place receive: a matching host
    ndarray leaf streams from the socket DIRECTLY into the template's
    buffer (no wire allocation), a jax.Array leaf lands via ``device_put``
    on the template's sharding. Leaves are written AS THEY ARRIVE, so a
    mid-stream failure leaves the template torn — even mid-leaf on this
    direct-stream path. That is safe only under the Manager's
    discard-and-retry heal protocol (a failed recv is reported, the step
    discarded, the heal retried); do not hand live state to a template
    outside that protocol. Structural drift between sender and template
    degrades the WHOLE receive to wire buffers with one warning (see
    ``template_leaves_for``).
    """

    def __init__(self, timeout: "float | timedelta" = 60.0, num_chunks: int = 0,
                 hostname: str = "",
                 state_dict_template: "Optional[Any]" = None) -> None:
        self._timeout = _to_seconds(timeout)
        self._num_chunks = num_chunks
        if state_dict_template is not None and not callable(state_dict_template):
            # same contract (and failure mode) as PGTransport: fail at
            # construction, not as an endlessly-retried heal error
            raise TypeError(
                "state_dict_template must be a zero-arg callable returning "
                "the template pytree, not the pytree itself "
                f"(got {type(state_dict_template).__name__})"
            )
        self._template_fn = state_dict_template
        # advertised heal address: overridable for fleets where
        # gethostname() is not peer-resolvable (e.g. k8s pods)
        self._hostname = hostname
        # Write-locked whenever there is NO servable checkpoint; readers are
        # in-flight HTTP requests (reference: http_transport.py:181-202).
        self._state_lock = RWLock(timeout=self._timeout)
        self._state_lock.w_acquire()
        self._have_state = False

        # One atomic snapshot per staging: (step, spec, payloads,
        # assignments). Handlers capture the reference ONCE per request, so
        # a restage mid-stream keeps serving the old snapshot consistently
        # instead of mixing two steps' leaves into one body (restaging swaps
        # a single attribute; the old snapshot's references stay alive for
        # in-flight readers).
        self._staged: Optional[tuple] = None

        # Delivery tracking: how many chunk fetches we expect for the staged
        # step vs. how many were served. disallow_checkpoint() grants a grace
        # window for lagging receivers before closing the window — without
        # this, a fast sender can reach should_commit and re-lock before a
        # healing peer started its fetch, failing the peer's recovery for a
        # full extra step.
        self._fetch_cond = threading.Condition()
        self._expected_fetches = 0
        self._served_fetches = 0

        transport = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                logger.debug("http_transport: " + fmt, *args)

            def do_GET(self) -> None:
                try:
                    # bound the streamed write: the chunk body is written
                    # while holding the state read lock, so a stalled
                    # receiver must time out rather than wedge
                    # disallow_checkpoint's write-acquire forever
                    self.connection.settimeout(transport._timeout)
                    parts = self.path.strip("/").split("/")
                    # /checkpoint/{step}/{what}
                    if len(parts) != 3 or parts[0] != "checkpoint":
                        self.send_error(404, "unknown path")
                        return
                    step = int(parts[1])
                    what = parts[2]
                    # Acquire the read lock OUTSIDE the streaming block:
                    # socket.timeout IS TimeoutError (py>=3.10), so a
                    # mid-stream write timeout must never reach a handler
                    # that answers with send_error — a 503 page injected
                    # into the middle of the frame stream would parse as
                    # leaf payload on the receiver.
                    if not transport._state_lock.r_acquire(
                        timeout=transport._timeout
                    ):
                        self.send_error(503, "checkpoint not available (locked)")
                        return
                    try:
                        # the read lock is held across the whole streamed
                        # write: disallow_checkpoint cannot yank the staged
                        # arrays out from under an in-flight response. The
                        # snapshot is captured once — restaging swaps the
                        # attribute atomically and cannot tear this body.
                        staged = transport._staged
                        if staged is None or staged[0] != step:
                            have = staged[0] if staged else None
                            self.send_error(
                                400,
                                f"serving step {have}, asked {step}",
                            )
                            return
                        if not transport._stream_response(self, staged, what):
                            self.send_error(404, f"unknown resource {what}")
                            return
                    except (BrokenPipeError, TimeoutError, OSError):
                        # receiver gone or stalled past the socket timeout:
                        # drop the connection; never write an error page
                        # into a partially-streamed body
                        self.close_connection = True
                        return
                    finally:
                        transport._state_lock.r_release()
                except (BrokenPipeError, socket.timeout):
                    pass  # receiver gone or stalled past the timeout
                except Exception as e:  # noqa: BLE001
                    logger.exception("http_transport handler failed")
                    try:
                        self.send_error(500, str(e))
                    except Exception:  # noqa: BLE001
                        pass

        self._server = ThreadingHTTPServer(("0.0.0.0", 0), _Handler)
        self._server.daemon_threads = True
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="torchft_http_ckpt"
        )
        self._serve_thread.start()

    # -- serving side -----------------------------------------------------
    def _stream_response(self, handler: Any, staged: tuple, what: str) -> bool:
        """Write the response for ``what`` (True if the resource exists)
        from the captured ``staged`` snapshot.

        Chunk bodies stream straight from the staged arrays: per range a
        24-byte [leaf_idx, offset, nbytes] frame then the raw byte range —
        never assembled in memory."""
        _step, spec, payloads, assignments = staged
        if what == "metadata":
            body = pickle.dumps((spec, len(assignments), _WIRE_VERSION))
            handler.send_response(200)
            handler.send_header("Content-Type", "application/octet-stream")
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
            return True
        if what.startswith("chunk_"):
            i = int(what[len("chunk_"):])
            if not (0 <= i < len(assignments)):
                return False
            ranges = assignments[i]
            total = sum(_FRAME_V2.size + ln for (_j, _off, ln) in ranges)
            handler.send_response(200)
            handler.send_header("Content-Type", "application/octet-stream")
            handler.send_header("Content-Length", str(total))
            handler.end_headers()
            for j, off, ln in ranges:
                mv = payload_memoryview(payloads[j])
                handler.wfile.write(_FRAME_V2.pack(j, off, ln))
                handler.wfile.write(mv[off : off + ln])
            with self._fetch_cond:
                # only count serves of the CURRENT staging: a stale-snapshot
                # serve completing after a restage must not satisfy the new
                # staging's grace window before its receivers have fetched
                current = self._staged
                if current is not None and current[0] == _step:
                    self._served_fetches += 1
                    self._fetch_cond.notify_all()
            return True
        return False

    def metadata(self) -> str:
        host = self._hostname or socket.gethostname()
        port = self._server.server_address[1]
        return f"http://{host}:{port}"

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: Any, timeout
    ) -> None:
        """Stage the state (host copy) and open the serving window.

        HTTP is pull-based: "send" = make available to ``dst_ranks`` until
        ``disallow_checkpoint`` re-locks (reference: http_transport.py:219-241).
        """
        spec, payloads = flatten_state(state_dict)
        leaf_nbytes = [m.nbytes for m in spec.leaves]
        total = sum(leaf_nbytes)
        if self._num_chunks > 0:
            chunk_bytes = max(1, -(-total // self._num_chunks))
        else:
            chunk_bytes = stream_chunk_bytes()
            if total > chunk_bytes * _AUTO_MAX_CHUNKS:
                chunk_bytes = -(-total // _AUTO_MAX_CHUNKS)
        assignments = plan_wire_ranges(leaf_nbytes, chunk_bytes)
        # single atomic swap: in-flight readers keep the old snapshot
        self._staged = (step, spec, payloads, assignments)
        with self._fetch_cond:
            self._expected_fetches = len(assignments) * max(len(dst_ranks), 0)
            self._served_fetches = 0
        if not self._have_state:
            self._have_state = True
            self._state_lock.w_release()

    def disallow_checkpoint(self, grace: Optional[float] = None) -> None:
        if self._have_state:
            # Grace window: give expected receivers a chance to fetch before
            # closing. Bounded so a crashed receiver can't stall the sender.
            grace = min(self._timeout, 10.0) if grace is None else grace
            with self._fetch_cond:
                self._fetch_cond.wait_for(
                    lambda: self._served_fetches >= self._expected_fetches,
                    timeout=grace,
                )
            if not self._state_lock.w_acquire(timeout=self._timeout):
                # A straggling receiver still streaming must NOT kill the
                # healthy donor (this raises out of should_commit). The
                # staged snapshot owns independent copies, so the in-flight
                # stream stays consistent even while training mutates live
                # state; just close the window for new requests and let the
                # next disallow re-attempt the lock.
                logger.warning(
                    "slow checkpoint receiver still streaming; closing the "
                    "serving window without re-locking"
                )
                self._staged = None
                return
            self._have_state = False
            self._staged = None

    # -- receiving side ---------------------------------------------------
    def recv_checkpoint(self, src_rank: int, metadata: str, step: int, timeout) -> Any:
        timeout_s = _to_seconds(timeout)
        base = f"{metadata}/checkpoint/{step}"

        def fetch(url: str) -> bytes:
            with urllib.request.urlopen(url, timeout=timeout_s) as r:
                return r.read()

        # tolerant unpack: v1 senders ship (spec, num_chunks), v2 appends
        # the wire version — unknown trailing fields are ignored
        spec, num_chunks, *meta_rest = pickle.loads(fetch(f"{base}/metadata"))
        version = meta_rest[0] if meta_rest else 1
        payloads: List[Optional[Any]] = [None] * len(spec.leaves)

        template_leaves: Optional[List[Any]] = None
        if self._template_fn is not None:
            # returns None (one warning) when the sender's tree STRUCTURE
            # differs from the template's — index-aligned placement would
            # risk streaming leaves into the wrong buffers
            template_leaves = template_leaves_for(
                spec, self._template_fn(), logger
            )

        def _host_target(meta, leaf_idx):
            """A host ndarray template leaf that can absorb this wire leaf
            lets the socket stream DIRECTLY into the resident buffer —
            zero wire-buffer alloc, the strongest in-place path."""
            if template_leaves is None or meta.kind != "array":
                return None
            t = template_leaves[leaf_idx]
            if can_absorb(t, meta.shape, meta.dtype, require_contiguous=True):
                return t
            return None

        # Per-leaf reassembly: ranges of one leaf may arrive on different
        # chunk-fetch threads, so the recv buffer is allocated once under a
        # lock and a bytes-remaining counter triggers finalization (device
        # placement / bytes conversion) exactly once, on the thread that
        # lands the last range — placement of a completed leaf overlaps
        # the wire transfer of the chunks still streaming.
        buf_lock = threading.Lock()
        buffers: List[Optional[Any]] = [None] * len(spec.leaves)
        direct: List[bool] = [False] * len(spec.leaves)
        remaining: List[int] = [m.nbytes for m in spec.leaves]

        def _buffer_for(leaf_idx: int) -> Any:
            with buf_lock:
                if buffers[leaf_idx] is None:
                    meta = spec.leaves[leaf_idx]
                    if meta.kind == "array":
                        target = _host_target(meta, leaf_idx)
                        if target is not None:
                            buffers[leaf_idx] = target
                            direct[leaf_idx] = True
                        else:
                            buffers[leaf_idx] = alloc_leaf(meta)
                    else:
                        buffers[leaf_idx] = bytearray(meta.nbytes)
                return buffers[leaf_idx]

        def _mark_written(leaf_idx: int, n: int) -> bool:
            """Credit ``n`` received bytes; True when the leaf is complete
            (finalize on the calling thread, outside the lock)."""
            with buf_lock:
                remaining[leaf_idx] -= n
                if remaining[leaf_idx] < 0:
                    raise ConnectionError(
                        f"leaf {leaf_idx}: overlapping/duplicate wire ranges"
                    )
                return remaining[leaf_idx] == 0 and payloads[leaf_idx] is None

        def _finish_leaf(leaf_idx: int) -> None:
            meta = spec.leaves[leaf_idx]
            arr = buffers[leaf_idx]
            if meta.kind == "array":
                if not direct[leaf_idx] and template_leaves is not None:
                    # device template (device_put) or a mismatch
                    # (warns "in-place receive degraded")
                    arr = place_leaf_like(arr, template_leaves[leaf_idx], logger)
                payloads[leaf_idx] = arr
            else:
                payloads[leaf_idx] = bytes(arr)

        timings = StreamTimings()
        stats_lock = threading.Lock()

        def fetch_chunk(i: int) -> None:
            """Stream one chunk: read each range frame, then read the body
            straight into the leaf's recv buffer at its offset."""
            frame = _FRAME_V2 if version >= 2 else _FRAME
            t0 = time.perf_counter()
            chunk_bytes = 0
            with urllib.request.urlopen(
                f"{base}/chunk_{i}", timeout=timeout_s
            ) as r:
                while True:
                    hdr = r.read(frame.size)
                    if not hdr:
                        break
                    if len(hdr) < frame.size:
                        raise ConnectionError(
                            f"chunk {i}: truncated frame header"
                        )
                    if version >= 2:
                        leaf_idx, off, nbytes = frame.unpack(hdr)
                    else:
                        leaf_idx, nbytes = frame.unpack(hdr)
                        off = 0
                    if not (0 <= leaf_idx < len(spec.leaves)):
                        raise ConnectionError(
                            f"chunk {i}: frame names leaf {leaf_idx} of "
                            f"{len(spec.leaves)}"
                        )
                    meta = spec.leaves[leaf_idx]
                    if version < 2 and nbytes != meta.nbytes:
                        # a short v1 frame would exit the read loop cleanly
                        # and leave the leaf — possibly a live template
                        # buffer — half-written with no error
                        raise ConnectionError(
                            f"chunk {i} leaf {leaf_idx}: frame carries "
                            f"{nbytes} bytes but the leaf spec says "
                            f"{meta.nbytes}"
                        )
                    if off < 0 or nbytes < 0 or off + nbytes > meta.nbytes:
                        raise ConnectionError(
                            f"chunk {i} leaf {leaf_idx}: range "
                            f"[{off}, {off + nbytes}) outside the leaf's "
                            f"{meta.nbytes} bytes"
                        )
                    buf = _buffer_for(leaf_idx)
                    if isinstance(buf, bytearray):
                        mv = memoryview(buf)[off : off + nbytes]
                    else:
                        mv = memoryview(buf.reshape(-1).view("u1"))[
                            off : off + nbytes
                        ]
                    got = 0
                    while got < nbytes:
                        n = r.readinto(mv[got:])
                        if not n:
                            raise ConnectionError(
                                f"chunk {i} truncated at leaf {leaf_idx} "
                                f"({got}/{nbytes} bytes of range)"
                            )
                        got += n
                    chunk_bytes += nbytes
                    if _mark_written(leaf_idx, nbytes):
                        _finish_leaf(leaf_idx)
            with stats_lock:
                timings.chunks.append(
                    ChunkStat(
                        nbytes=chunk_bytes,
                        transfer_s=time.perf_counter() - t0,
                    )
                )
                timings.total_bytes += chunk_bytes

        t_all = time.perf_counter()
        with ThreadPoolExecutor(max_workers=max(1, min(num_chunks, 8))) as ex:
            list(ex.map(fetch_chunk, range(num_chunks)))
        timings.total_s = time.perf_counter() - t_all
        # zero-byte leaves get no range bytes on v2 wires; finalize them
        for i, rem in enumerate(remaining):
            if rem == 0 and payloads[i] is None:
                _buffer_for(i)
                _finish_leaf(i)
        missing = [i for i, p in enumerate(payloads) if p is None]
        if missing:
            raise RuntimeError(f"checkpoint chunks missing leaves {missing}")
        self._last_recv_timings = timings
        return unflatten_state(spec, payloads)  # type: ignore[arg-type]

    def shutdown(self, wait: bool = True) -> None:
        self._server.shutdown()
        self._server.server_close()
        if wait:
            self._serve_thread.join(timeout=5)
