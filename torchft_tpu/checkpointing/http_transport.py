"""HTTP checkpoint transport: the default live-recovery path.

Design mirror of the reference HTTPTransport
(torchft/checkpointing/http_transport.py:38-266): a threaded HTTP server
serving ``/checkpoint/{step}/{metadata|chunk_{i}}``, gated by an RWLock so
serving can be disallowed while the optimizer mutates state; receivers fetch
chunks in parallel and reassemble the pytree.
"""

from __future__ import annotations

import logging
import pickle
import socket
import threading
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, List, Optional

from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.checkpointing._serialization import (
    TreeSpecPayload,
    flatten_state,
    split_chunks,
    unflatten_state,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport

logger = logging.getLogger(__name__)

__all__ = ["HTTPTransport"]


def _to_seconds(timeout: "float | timedelta") -> float:
    return timeout.total_seconds() if isinstance(timeout, timedelta) else float(timeout)


class HTTPTransport(CheckpointTransport[Any]):
    """Serve checkpoints over HTTP; receive with parallel chunk fetch.

    ``num_chunks=0`` serves everything as one chunk.
    """

    def __init__(self, timeout: "float | timedelta" = 60.0, num_chunks: int = 0) -> None:
        self._timeout = _to_seconds(timeout)
        self._num_chunks = num_chunks
        # Write-locked whenever there is NO servable checkpoint; readers are
        # in-flight HTTP requests (reference: http_transport.py:181-202).
        self._state_lock = RWLock(timeout=self._timeout)
        self._state_lock.w_acquire()
        self._have_state = False

        self._step: Optional[int] = None
        self._spec: Optional[TreeSpecPayload] = None
        self._chunks: Optional[List[bytes]] = None  # pre-assembled chunk bodies

        # Delivery tracking: how many chunk fetches we expect for the staged
        # step vs. how many were served. disallow_checkpoint() grants a grace
        # window for lagging receivers before closing the window — without
        # this, a fast sender can reach should_commit and re-lock before a
        # healing peer started its fetch, failing the peer's recovery for a
        # full extra step.
        self._fetch_cond = threading.Condition()
        self._expected_fetches = 0
        self._served_fetches = 0

        transport = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                logger.debug("http_transport: " + fmt, *args)

            def do_GET(self) -> None:
                try:
                    parts = self.path.strip("/").split("/")
                    # /checkpoint/{step}/{what}
                    if len(parts) != 3 or parts[0] != "checkpoint":
                        self.send_error(404, "unknown path")
                        return
                    step = int(parts[1])
                    what = parts[2]
                    try:
                        with transport._state_lock.r_lock(timeout=transport._timeout):
                            if transport._step != step:
                                self.send_error(
                                    400,
                                    f"serving step {transport._step}, asked {step}",
                                )
                                return
                            body = transport._body_for(what)
                    except TimeoutError:
                        self.send_error(503, "checkpoint not available (locked)")
                        return
                    if body is None:
                        self.send_error(404, f"unknown resource {what}")
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001
                    logger.exception("http_transport handler failed")
                    try:
                        self.send_error(500, str(e))
                    except Exception:  # noqa: BLE001
                        pass

        self._server = ThreadingHTTPServer(("0.0.0.0", 0), _Handler)
        self._server.daemon_threads = True
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="torchft_http_ckpt"
        )
        self._serve_thread.start()

    # -- serving side -----------------------------------------------------
    def _body_for(self, what: str) -> Optional[bytes]:
        assert self._spec is not None and self._chunks is not None
        if what == "metadata":
            return pickle.dumps((self._spec, len(self._chunks)))
        if what.startswith("chunk_"):
            i = int(what[len("chunk_"):])
            if 0 <= i < len(self._chunks):
                with self._fetch_cond:
                    self._served_fetches += 1
                    self._fetch_cond.notify_all()
                return self._chunks[i]
        return None

    def metadata(self) -> str:
        host = socket.gethostname()
        port = self._server.server_address[1]
        return f"http://{host}:{port}"

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: Any, timeout
    ) -> None:
        """Stage the state (host copy) and open the serving window.

        HTTP is pull-based: "send" = make available to ``dst_ranks`` until
        ``disallow_checkpoint`` re-locks (reference: http_transport.py:219-241).
        """
        spec, payloads = flatten_state(state_dict)
        num = self._num_chunks or 1
        assignments = split_chunks([len(p) for p in payloads], num)
        chunks = [
            pickle.dumps([(i, payloads[i]) for i in idxs]) for idxs in assignments
        ]
        self._step = step
        self._spec = spec
        self._chunks = chunks
        with self._fetch_cond:
            self._expected_fetches = len(chunks) * max(len(dst_ranks), 0)
            self._served_fetches = 0
        if not self._have_state:
            self._have_state = True
            self._state_lock.w_release()

    def disallow_checkpoint(self, grace: Optional[float] = None) -> None:
        if self._have_state:
            # Grace window: give expected receivers a chance to fetch before
            # closing. Bounded so a crashed receiver can't stall the sender.
            grace = min(self._timeout, 10.0) if grace is None else grace
            with self._fetch_cond:
                self._fetch_cond.wait_for(
                    lambda: self._served_fetches >= self._expected_fetches,
                    timeout=grace,
                )
            if not self._state_lock.w_acquire(timeout=self._timeout):
                raise TimeoutError(
                    "timed out waiting for in-flight checkpoint reads to finish"
                )
            self._have_state = False
            self._spec = None
            self._chunks = None
            self._step = None

    # -- receiving side ---------------------------------------------------
    def recv_checkpoint(self, src_rank: int, metadata: str, step: int, timeout) -> Any:
        timeout_s = _to_seconds(timeout)
        base = f"{metadata}/checkpoint/{step}"

        def fetch(url: str) -> bytes:
            with urllib.request.urlopen(url, timeout=timeout_s) as r:
                return r.read()

        spec, num_chunks = pickle.loads(fetch(f"{base}/metadata"))
        payloads: List[Optional[bytes]] = [None] * len(spec.leaves)
        with ThreadPoolExecutor(max_workers=max(1, min(num_chunks, 8))) as ex:
            bodies = list(
                ex.map(lambda i: fetch(f"{base}/chunk_{i}"), range(num_chunks))
            )
        for body in bodies:
            for leaf_idx, buf in pickle.loads(body):
                payloads[leaf_idx] = buf
        missing = [i for i, p in enumerate(payloads) if p is None]
        if missing:
            raise RuntimeError(f"checkpoint chunks missing leaves {missing}")
        return unflatten_state(spec, payloads)  # type: ignore[arg-type]

    def shutdown(self, wait: bool = True) -> None:
        self._server.shutdown()
        self._server.server_close()
        if wait:
            self._serve_thread.join(timeout=5)
