"""HTTP checkpoint transport: the default live-recovery path.

Design mirror of the reference HTTPTransport
(torchft/checkpointing/http_transport.py:38-266): a threaded HTTP server
serving ``/checkpoint/{step}/{metadata|chunk_{i}}``, gated by an RWLock so
serving can be disallowed while the optimizer mutates state; receivers fetch
chunks in parallel and reassemble the pytree.

Both directions stream (reference `_streaming_save/_load`,
http_transport.py:219-266): the sender serves leaf payloads straight from
the staged host arrays — one [leaf_idx, offset, nbytes] frame header then
the raw byte range, no pre-pickled chunk bodies — and the receiver reads
each frame directly into the leaf's final preallocated array
(``readinto``). Peak host overhead is O(stream buffer), not O(payload),
which is what makes 12GB-class state dicts transferable at 8B scale.

Wire chunks are BYTE ranges (``plan_wire_ranges``), not whole leaves: a
single multi-GB fused parameter buffer splits across chunks, so parallel
chunk fetches overlap its network transfer with the device placement of
already-complete leaves instead of store-and-forwarding one blob.

Wire version 3 adds receiver-opt-in integrity + resume to the chunk wire:
a ``crc=1`` query appends a 4-byte crc32 trailer over the canonical chunk
body, and ``offset=N`` resumes the body mid-stream from byte ``N`` — the
receiver keeps a running crc across reconnects, so a stalled transfer
resumes from the last received byte and a corrupt chunk is detected and
re-fetched instead of silently loaded into params. Both features ride
query params the v2 server never saw, and a v3 receiver only sends them
to peers whose metadata advertises v3, so v2<->v3 interop in either
direction is byte-identical to v2. v1 senders (whole-leaf
``[leaf_idx, nbytes]`` frames) are still understood on receive.

``recv_checkpoint_multi`` layers mid-heal failover on top: an ordered list
of candidate sources is tried under one deadline, and because
``plan_wire_ranges`` is deterministic and every max-step peer stages the
same state, a chunk half-fetched from a dying source resumes at the same
byte offset on the next peer.
"""

from __future__ import annotations

import logging
import pickle
import socket
import struct
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import zlib
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from torchft_tpu.retry import RetryPolicy

from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.checkpointing._serialization import (
    TreeSpecPayload,
    alloc_leaf,
    can_absorb,
    flatten_state,
    payload_memoryview,
    place_leaf_like,
    template_leaves_for,
    unflatten_state,
)
from torchft_tpu.checkpointing.transport import (
    CheckpointTransport,
    ChunkStat,
    StreamTimings,
    plan_wire_ranges,
    stream_chunk_bytes,
)

logger = logging.getLogger(__name__)

__all__ = ["HTTPTransport"]

_FRAME = struct.Struct("<qq")  # v1: leaf_idx, nbytes (whole leaf)
_FRAME_V2 = struct.Struct("<qqq")  # leaf_idx, offset, nbytes (byte range)
_CRC = struct.Struct("<I")  # v3 opt-in chunk trailer: crc32 of the body
_WIRE_VERSION = 3
# cap on auto-planned chunks (num_chunks=0): bounds fetch parallelism and
# the per-chunk frame overhead on huge states
_AUTO_MAX_CHUNKS = 8


def _to_seconds(timeout: "float | timedelta") -> float:
    return timeout.total_seconds() if isinstance(timeout, timedelta) else float(timeout)


class HTTPTransport(CheckpointTransport[Any]):
    """Serve checkpoints over HTTP; receive with parallel chunk fetch.

    ``num_chunks=0`` auto-plans byte-range chunks of roughly
    ``TORCHFT_STREAM_CHUNK_BYTES`` (default 32 MiB, at most 8 chunks), so
    the default transport pipelines large heals; ``num_chunks>0`` forces
    that many chunks. Chunk boundaries are byte offsets, not leaf
    boundaries — one huge leaf still streams as multiple chunks.

    ``state_dict_template`` (zero-arg callable returning a pytree, same
    contract as PGTransport's) enables in-place receive: a matching host
    ndarray leaf streams from the socket DIRECTLY into the template's
    buffer (no wire allocation), a jax.Array leaf lands via ``device_put``
    on the template's sharding. Leaves are written AS THEY ARRIVE, so a
    mid-stream failure leaves the template torn — even mid-leaf on this
    direct-stream path. That is safe only under the Manager's
    discard-and-retry heal protocol (a failed recv is reported, the step
    discarded, the heal retried); do not hand live state to a template
    outside that protocol. Structural drift between sender and template
    degrades the WHOLE receive to wire buffers with one warning (see
    ``template_leaves_for``).
    """

    def __init__(self, timeout: "float | timedelta" = 60.0, num_chunks: int = 0,
                 hostname: str = "",
                 state_dict_template: "Optional[Any]" = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 client_only: bool = False) -> None:
        self._timeout = _to_seconds(timeout)
        # client_only: a pure receiver (serving-plane workers, bootstrap
        # pulls) that never stages state — skip binding a listener so a
        # fleet of pullers doesn't burn a port (and a thread) each
        self._client_only = client_only
        self._num_chunks = num_chunks
        # per-chunk same-source retry budget + backoff for the recv side
        self._retry_policy = (
            retry_policy if retry_policy is not None else RetryPolicy.from_env()
        )
        # test-only serve-side fault injection (see inject_chunk_fault)
        self._fault_lock = threading.Lock()
        self._chunk_faults: List[Dict[str, int]] = []
        if state_dict_template is not None and not callable(state_dict_template):
            # same contract (and failure mode) as PGTransport: fail at
            # construction, not as an endlessly-retried heal error
            raise TypeError(
                "state_dict_template must be a zero-arg callable returning "
                "the template pytree, not the pytree itself "
                f"(got {type(state_dict_template).__name__})"
            )
        self._template_fn = state_dict_template
        # advertised heal address: overridable for fleets where
        # gethostname() is not peer-resolvable (e.g. k8s pods)
        self._hostname = hostname
        # Write-locked whenever there is NO servable checkpoint; readers are
        # in-flight HTTP requests (reference: http_transport.py:181-202).
        self._state_lock = RWLock(timeout=self._timeout)
        self._state_lock.w_acquire()
        self._have_state = False

        # One atomic snapshot per staging: (step, spec, payloads,
        # assignments). Handlers capture the reference ONCE per request, so
        # a restage mid-stream keeps serving the old snapshot consistently
        # instead of mixing two steps' leaves into one body (restaging swaps
        # a single attribute; the old snapshot's references stay alive for
        # in-flight readers).
        self._staged: Optional[tuple] = None

        # Delivery tracking: how many chunk fetches we expect for the staged
        # step vs. how many were served. disallow_checkpoint() grants a grace
        # window for lagging receivers before closing the window — without
        # this, a fast sender can reach should_commit and re-lock before a
        # healing peer started its fetch, failing the peer's recovery for a
        # full extra step.
        self._fetch_cond = threading.Condition()
        self._expected_fetches = 0
        self._served_fetches = 0

        transport = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                logger.debug("http_transport: " + fmt, *args)

            def do_GET(self) -> None:
                try:
                    # bound the streamed write: the chunk body is written
                    # while holding the state read lock, so a stalled
                    # receiver must time out rather than wedge
                    # disallow_checkpoint's write-acquire forever
                    self.connection.settimeout(transport._timeout)
                    raw_path, _, raw_query = self.path.partition("?")
                    parts = raw_path.strip("/").split("/")
                    # /checkpoint/{step}/{what}[?crc=1&offset=N]
                    if len(parts) != 3 or parts[0] != "checkpoint":
                        self.send_error(404, "unknown path")
                        return
                    step = int(parts[1])
                    what = parts[2]
                    query = urllib.parse.parse_qs(raw_query)
                    # Acquire the read lock OUTSIDE the streaming block:
                    # socket.timeout IS TimeoutError (py>=3.10), so a
                    # mid-stream write timeout must never reach a handler
                    # that answers with send_error — a 503 page injected
                    # into the middle of the frame stream would parse as
                    # leaf payload on the receiver.
                    if not transport._state_lock.r_acquire(
                        timeout=transport._timeout
                    ):
                        self.send_error(503, "checkpoint not available (locked)")
                        return
                    try:
                        # the read lock is held across the whole streamed
                        # write: disallow_checkpoint cannot yank the staged
                        # arrays out from under an in-flight response. The
                        # snapshot is captured once — restaging swaps the
                        # attribute atomically and cannot tear this body.
                        staged = transport._staged
                        if staged is None or staged[0] != step:
                            have = staged[0] if staged else None
                            self.send_error(
                                400,
                                f"serving step {have}, asked {step}",
                            )
                            return
                        if not transport._stream_response(
                            self, staged, what, query
                        ):
                            self.send_error(404, f"unknown resource {what}")
                            return
                    except (BrokenPipeError, TimeoutError, OSError):
                        # receiver gone or stalled past the socket timeout:
                        # drop the connection; never write an error page
                        # into a partially-streamed body
                        self.close_connection = True
                        return
                    finally:
                        transport._state_lock.r_release()
                except (BrokenPipeError, socket.timeout):
                    pass  # receiver gone or stalled past the timeout
                except Exception as e:  # noqa: BLE001
                    logger.exception("http_transport handler failed")
                    try:
                        self.send_error(500, str(e))
                    except Exception:  # noqa: BLE001
                        pass

        if client_only:
            self._server = None
            self._serve_thread = None
        else:
            self._server = ThreadingHTTPServer(("0.0.0.0", 0), _Handler)
            self._server.daemon_threads = True
            self._serve_thread = threading.Thread(
                target=self._server.serve_forever, daemon=True,
                name="torchft_http_ckpt",
            )
            self._serve_thread.start()

    # -- serving side -----------------------------------------------------
    def inject_chunk_fault(self, chunk: int, mode: str, times: int = 1) -> None:
        """Test-only: make the next ``times`` serves of ``chunk`` fail.

        ``mode="corrupt"``: one payload byte of the served body is flipped
        while the crc32 trailer stays canonical — the receiver detects the
        mismatch and re-fetches. ``mode="die"``: the connection drops
        roughly halfway through the requested span — models the source
        dying mid-heal. ``times=-1`` faults every serve (a permanently-dead
        source, forcing receiver failover)."""
        if mode not in ("corrupt", "die"):
            raise ValueError(f"unknown fault mode {mode!r}")
        with self._fault_lock:
            self._chunk_faults.append(
                {"chunk": chunk, "mode": mode, "times": times}  # type: ignore[dict-item]
            )

    def _take_fault(self, chunk: int) -> Optional[str]:
        with self._fault_lock:
            for f in self._chunk_faults:
                if f["chunk"] == chunk and f["times"] != 0:
                    if f["times"] > 0:
                        f["times"] -= 1
                    return f["mode"]  # type: ignore[return-value]
        return None

    def _stream_response(
        self, handler: Any, staged: tuple, what: str, query: dict
    ) -> bool:
        """Write the response for ``what`` (True if the resource exists)
        from the captured ``staged`` snapshot.

        Chunk bodies stream straight from the staged arrays: per range a
        24-byte [leaf_idx, offset, nbytes] frame then the raw byte range —
        never assembled in memory. ``offset=N`` serves the body from byte
        ``N`` (resume); ``crc=1`` appends a 4-byte crc32 trailer over the
        CANONICAL full body, so a resuming receiver's running crc still
        verifies end to end."""
        _step, spec, payloads, assignments = staged
        if what == "metadata":
            body = pickle.dumps((spec, len(assignments), _WIRE_VERSION))
            handler.send_response(200)
            handler.send_header("Content-Type", "application/octet-stream")
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
            return True
        if what.startswith("chunk_"):
            i = int(what[len("chunk_"):])
            if not (0 <= i < len(assignments)):
                return False
            want_crc = query.get("crc", ["0"])[0] == "1"
            start = int(query.get("offset", ["0"])[0])
            ranges = assignments[i]
            body_len = sum(_FRAME_V2.size + ln for (_j, _off, ln) in ranges)
            if start < 0 or start > body_len:
                return False
            fault = self._take_fault(i)
            die_after: Optional[int] = None
            if fault == "die":
                # drop the connection roughly halfway through the span
                die_after = max((body_len - start) // 2, 1)
            total = body_len - start + (_CRC.size if want_crc else 0)
            handler.send_response(200)
            handler.send_header("Content-Type", "application/octet-stream")
            handler.send_header("Content-Length", str(total))
            handler.end_headers()
            crc = 0
            pos = 0  # canonical body cursor
            written = 0
            corrupt_pending = fault == "corrupt"
            for j, off, ln in ranges:
                mv = payload_memoryview(payloads[j])
                for is_payload, seg in (
                    (False, _FRAME_V2.pack(j, off, ln)),
                    (True, mv[off : off + ln]),
                ):
                    seg_len = len(seg)
                    if want_crc:
                        crc = zlib.crc32(seg, crc)
                    if pos + seg_len > start:
                        lo = max(0, start - pos)
                        out = seg[lo:]
                        if corrupt_pending and is_payload and len(out):
                            out = bytearray(out)
                            out[0] ^= 0xFF
                            corrupt_pending = False
                        if die_after is not None and written + len(out) >= die_after:
                            handler.wfile.write(out[: max(die_after - written, 0)])
                            handler.close_connection = True
                            return True
                        handler.wfile.write(out)
                        written += len(out)
                    pos += seg_len
            if want_crc:
                handler.wfile.write(_CRC.pack(crc & 0xFFFFFFFF))
            with self._fetch_cond:
                # only count serves of the CURRENT staging: a stale-snapshot
                # serve completing after a restage must not satisfy the new
                # staging's grace window before its receivers have fetched
                current = self._staged
                if current is not None and current[0] == _step:
                    self._served_fetches += 1
                    self._fetch_cond.notify_all()
            return True
        return False

    def metadata(self) -> str:
        if self._server is None:
            raise RuntimeError(
                "client_only transport has no serve address (metadata())"
            )
        host = self._hostname or socket.gethostname()
        port = self._server.server_address[1]
        return f"http://{host}:{port}"

    def staged_step(self) -> "Optional[int]":
        """Step currently staged for serving, or None when the window is
        closed (serving-plane introspection; reads one attribute)."""
        staged = self._staged
        return staged[0] if staged is not None else None

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: Any, timeout
    ) -> None:
        """Stage the state (host copy) and open the serving window.

        HTTP is pull-based: "send" = make available to ``dst_ranks`` until
        ``disallow_checkpoint`` re-locks (reference: http_transport.py:219-241).
        """
        if self._server is None:
            raise RuntimeError("client_only transport cannot stage checkpoints")
        spec, payloads = flatten_state(state_dict)
        leaf_nbytes = [m.nbytes for m in spec.leaves]
        total = sum(leaf_nbytes)
        if self._num_chunks > 0:
            chunk_bytes = max(1, -(-total // self._num_chunks))
        else:
            chunk_bytes = stream_chunk_bytes()
            if total > chunk_bytes * _AUTO_MAX_CHUNKS:
                chunk_bytes = -(-total // _AUTO_MAX_CHUNKS)
        assignments = plan_wire_ranges(leaf_nbytes, chunk_bytes)
        # single atomic swap: in-flight readers keep the old snapshot
        self._staged = (step, spec, payloads, assignments)
        with self._fetch_cond:
            self._expected_fetches = len(assignments) * max(len(dst_ranks), 0)
            self._served_fetches = 0
        if not self._have_state:
            self._have_state = True
            self._state_lock.w_release()

    def disallow_checkpoint(self, grace: Optional[float] = None) -> None:
        if self._have_state:
            # Grace window: give expected receivers a chance to fetch before
            # closing. Bounded so a crashed receiver can't stall the sender.
            grace = min(self._timeout, 10.0) if grace is None else grace
            with self._fetch_cond:
                self._fetch_cond.wait_for(
                    lambda: self._served_fetches >= self._expected_fetches,
                    timeout=grace,
                )
            if not self._state_lock.w_acquire(timeout=self._timeout):
                # A straggling receiver still streaming must NOT kill the
                # healthy donor (this raises out of should_commit). The
                # staged snapshot owns independent copies, so the in-flight
                # stream stays consistent even while training mutates live
                # state; just close the window for new requests and let the
                # next disallow re-attempt the lock.
                logger.warning(
                    "slow checkpoint receiver still streaming; closing the "
                    "serving window without re-locking"
                )
                self._staged = None
                return
            self._have_state = False
            self._staged = None

    # -- receiving side ---------------------------------------------------
    supports_multi_source = True

    def recv_checkpoint(self, src_rank: int, metadata: str, step: int, timeout) -> Any:
        return self.recv_checkpoint_multi(
            [(f"replica_rank_{src_rank}", lambda: metadata)], step, timeout
        )

    def recv_checkpoint_multi(
        self,
        sources: List[Tuple[str, Callable[[], str]]],
        step: int,
        timeout,
        on_event: Optional[Callable[..., None]] = None,
    ) -> Any:
        """Fetch ``step`` from an ordered list of candidate sources under
        one deadline, resuming and failing over mid-transfer.

        Chunk progress (byte offset, running crc, pending credits) survives
        a source switch: same-step peers stage identical states and
        ``plan_wire_ranges`` is deterministic, so as long as the next peer's
        metadata matches the plan signature, a half-fetched chunk continues
        at its last received byte on the new peer. A signature mismatch
        (different chunking config) restarts the receive from scratch."""
        timeout_s = _to_seconds(timeout)
        deadline = time.monotonic() + timeout_s
        emit = on_event if on_event is not None else (lambda kind, **f: None)
        timings = StreamTimings()
        t_all = time.perf_counter()
        rs: Optional[_RecvState] = None
        last_exc: Optional[Exception] = None
        tried = 0
        for src_i, (label, metadata_fn) in enumerate(sources):
            if time.monotonic() >= deadline:
                break
            if src_i > 0:
                timings.failovers += 1
                emit("heal_failover", source=label, prior_error=repr(last_exc))
            tried += 1
            try:
                base = f"{metadata_fn()}/checkpoint/{step}"
                meta_timeout = min(
                    timeout_s, max(deadline - time.monotonic(), 0.001)
                )
                with urllib.request.urlopen(
                    f"{base}/metadata", timeout=meta_timeout
                ) as r:
                    raw_meta = r.read()
            except Exception as e:  # noqa: BLE001 — any peer error -> next peer
                last_exc = e
                continue
            # tolerant unpack: v1 senders ship (spec, num_chunks), v2+
            # appends the wire version — unknown trailing fields ignored
            spec, num_chunks, *meta_rest = pickle.loads(raw_meta)
            version = meta_rest[0] if meta_rest else 1
            sig = (num_chunks, tuple(m.nbytes for m in spec.leaves))
            if rs is None or rs.sig != sig:
                if rs is not None:
                    logger.warning(
                        "heal source %s plans %s, prior source planned %s; "
                        "restarting the receive from scratch", label, sig, rs.sig
                    )
                rs = _RecvState(spec, num_chunks, self._template_fn)
            try:
                self._fetch_all(
                    rs, base, version, deadline, timeout_s, timings, emit, label
                )
            except Exception as e:  # noqa: BLE001 — exhausted on this peer
                last_exc = e
                continue
            # success: finalize zero-byte leaves (no range bytes on the
            # wire), check completeness, reassemble
            for i, rem in enumerate(rs.remaining):
                if rem == 0 and rs.payloads[i] is None:
                    rs.buffer_for(i)
                    rs.finish_leaf(i)
            missing = [i for i, p in enumerate(rs.payloads) if p is None]
            if missing:
                raise RuntimeError(f"checkpoint chunks missing leaves {missing}")
            timings.total_s = time.perf_counter() - t_all
            self._last_recv_timings = timings
            return unflatten_state(rs.spec, rs.payloads)  # type: ignore[arg-type]
        timings.total_s = time.perf_counter() - t_all
        self._last_recv_timings = timings
        raise RuntimeError(
            f"heal failed: all {tried}/{len(sources)} source(s) exhausted "
            f"within {timeout_s:.1f}s (last error: {last_exc!r})"
        ) from last_exc

    def _fetch_all(
        self,
        rs: "_RecvState",
        base: str,
        version: int,
        deadline: float,
        timeout_s: float,
        timings: StreamTimings,
        emit: Callable[..., None],
        label: str,
    ) -> None:
        """Fetch every unfinished chunk from one source in parallel, with a
        per-chunk same-source retry loop (resume on stall when the source
        speaks v3, full chunk refetch on crc mismatch)."""
        todo = [st for st in rs.chunk_states if not st.done]
        if not todo:
            return
        policy = self._retry_policy

        def run(st: "_ChunkFetch") -> None:
            attempts = 0
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"heal deadline exhausted before chunk {st.i}"
                    )
                try:
                    self._fetch_chunk_once(
                        rs, st, base, version, min(timeout_s, remaining), timings
                    )
                    return
                except _ChunkCrcError as e:
                    # corrupt bytes are never credited/finalized: throw away
                    # the chunk's progress and re-fetch it from byte 0
                    st.reset()
                    with rs.stats_lock:
                        timings.crc_failures += 1
                    emit("chunk_crc_failure", chunk=st.i, source=label)
                    err: Exception = e
                except (ConnectionError, TimeoutError, OSError) as e:
                    if version < 3:
                        # v2 peers can't serve a body suffix: restart chunk
                        st.reset()
                    err = e
                attempts += 1
                if attempts >= policy.max_attempts:
                    raise err
                with rs.stats_lock:
                    timings.retries += 1
                emit(
                    "heal_retry",
                    chunk=st.i,
                    source=label,
                    attempt=attempts,
                    resume_offset=st.body_off,
                    error=repr(err),
                )
                pause = policy.backoff_s(attempts + 1)
                time.sleep(min(pause, max(deadline - time.monotonic(), 0)))

        with ThreadPoolExecutor(max_workers=max(1, min(len(todo), 8))) as ex:
            futs = [ex.submit(run, st) for st in todo]
            errs = [f.exception() for f in futs]
        for e in errs:
            if e is not None:
                raise e  # type: ignore[misc]

    def _fetch_chunk_once(
        self,
        rs: "_RecvState",
        st: "_ChunkFetch",
        base: str,
        version: int,
        timeout_s: float,
        timings: StreamTimings,
    ) -> None:
        """One streaming attempt at chunk ``st.i``: read range frames and
        stream payloads straight into the leaf recv buffers, resuming from
        ``st.body_off`` when the source speaks v3.

        Leaf byte credits are DEFERRED to the chunk's pending list and only
        applied after the whole chunk verifies (v3: crc trailer matches;
        v1/v2: clean EOF), so a corrupt chunk can be re-fetched with the
        buffer rewrites staying idempotent and no leaf is ever finalized
        from unverified bytes."""
        frame = _FRAME_V2 if version >= 2 else _FRAME
        want_crc = version >= 3
        url = f"{base}/chunk_{st.i}"
        if want_crc:
            params = ["crc=1"]
            if st.body_off:
                params.append(f"offset={st.body_off}")
            url += "?" + "&".join(params)
        t0 = time.perf_counter()
        attempt_bytes = 0
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            while True:
                if st.cur is None:
                    hdr = _read_upto(r, frame.size)
                    if not hdr:
                        if want_crc:
                            raise ConnectionError(
                                f"chunk {st.i}: stream ended before crc trailer"
                            )
                        break  # v1/v2: clean end of chunk
                    if want_crc and len(hdr) == _CRC.size:
                        expected = _CRC.unpack(hdr)[0]
                        if st.crc & 0xFFFFFFFF != expected:
                            raise _ChunkCrcError(
                                f"chunk {st.i}: crc32 mismatch "
                                f"(got {st.crc & 0xFFFFFFFF:#010x}, "
                                f"trailer {expected:#010x})"
                            )
                        break  # verified
                    if len(hdr) < frame.size:
                        # partial header bytes are NOT counted in body_off,
                        # so a resume re-reads the whole header
                        raise ConnectionError(
                            f"chunk {st.i}: truncated frame header"
                        )
                    if version >= 2:
                        leaf_idx, off, nbytes = frame.unpack(hdr)
                    else:
                        leaf_idx, nbytes = frame.unpack(hdr)
                        off = 0
                    if not (0 <= leaf_idx < len(rs.spec.leaves)):
                        raise ConnectionError(
                            f"chunk {st.i}: frame names leaf {leaf_idx} of "
                            f"{len(rs.spec.leaves)}"
                        )
                    meta = rs.spec.leaves[leaf_idx]
                    if version < 2 and nbytes != meta.nbytes:
                        # a short v1 frame would exit the read loop cleanly
                        # and leave the leaf — possibly a live template
                        # buffer — half-written with no error
                        raise ConnectionError(
                            f"chunk {st.i} leaf {leaf_idx}: frame carries "
                            f"{nbytes} bytes but the leaf spec says "
                            f"{meta.nbytes}"
                        )
                    if off < 0 or nbytes < 0 or off + nbytes > meta.nbytes:
                        raise ConnectionError(
                            f"chunk {st.i} leaf {leaf_idx}: range "
                            f"[{off}, {off + nbytes}) outside the leaf's "
                            f"{meta.nbytes} bytes"
                        )
                    if want_crc:
                        st.crc = zlib.crc32(hdr, st.crc)
                    st.body_off += frame.size
                    st.cur = (leaf_idx, off, nbytes, 0)
                leaf_idx, off, nbytes, got = st.cur
                buf = rs.buffer_for(leaf_idx)
                if isinstance(buf, bytearray):
                    span = memoryview(buf)[off : off + nbytes]
                else:
                    span = memoryview(buf.reshape(-1).view("u1"))[
                        off : off + nbytes
                    ]
                while got < nbytes:
                    n = r.readinto(span[got:])
                    if not n:
                        raise ConnectionError(
                            f"chunk {st.i} truncated at leaf {leaf_idx} "
                            f"({got}/{nbytes} bytes of range)"
                        )
                    if want_crc:
                        st.crc = zlib.crc32(span[got : got + n], st.crc)
                    st.body_off += n
                    got += n
                    st.cur = (leaf_idx, off, nbytes, got)
                    attempt_bytes += n
                st.pending.append((leaf_idx, nbytes))
                st.cur = None
        # chunk verified (or v1/v2-complete): apply the deferred credits,
        # finalizing any leaves this chunk completed
        for leaf_idx, n in st.pending:
            if rs.mark_written(leaf_idx, n):
                rs.finish_leaf(leaf_idx)
        st.pending = []
        st.done = True
        with rs.stats_lock:
            timings.chunks.append(
                ChunkStat(
                    nbytes=attempt_bytes,
                    transfer_s=time.perf_counter() - t0,
                )
            )
            timings.total_bytes += attempt_bytes

    def shutdown(self, wait: bool = True) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if wait:
            self._serve_thread.join(timeout=5)


class _ChunkCrcError(ConnectionError):
    """The chunk's crc32 trailer did not match the received body."""


def _read_upto(r: Any, n: int) -> bytes:
    """Read up to ``n`` bytes, short only at EOF (loops over short reads)."""
    buf = b""
    while len(buf) < n:
        got = r.read(n - len(buf))
        if not got:
            break
        buf += got
    return buf


class _ChunkFetch:
    """Resumable per-chunk fetch state, surviving reconnects and source
    failovers: ``body_off`` is the canonical-body byte to resume from,
    ``crc`` the running crc32 of everything consumed so far, ``cur`` a
    partially-read range ``(leaf_idx, off, nbytes, got)``, and ``pending``
    the leaf byte credits deferred until the chunk verifies."""

    __slots__ = ("i", "body_off", "crc", "cur", "pending", "done")

    def __init__(self, i: int) -> None:
        self.i = i
        self.reset()

    def reset(self) -> None:
        self.body_off = 0
        self.crc = 0
        self.cur: Optional[Tuple[int, int, int, int]] = None
        self.pending: List[Tuple[int, int]] = []
        self.done = False


class _RecvState:
    """Shared reassembly state of one multi-source receive: recv buffers,
    per-leaf byte accounting, and the per-chunk fetch states.

    Per-leaf reassembly: ranges of one leaf may arrive on different
    chunk-fetch threads, so the recv buffer is allocated once under a lock
    and a bytes-remaining counter triggers finalization (device placement /
    bytes conversion) exactly once, on the thread whose chunk lands the
    leaf's last verified range — placement of completed leaves overlaps the
    wire transfer of the chunks still streaming."""

    def __init__(self, spec: Any, num_chunks: int, template_fn: Any) -> None:
        self.spec = spec
        self.num_chunks = num_chunks
        self.sig = (num_chunks, tuple(m.nbytes for m in spec.leaves))
        self.payloads: List[Optional[Any]] = [None] * len(spec.leaves)
        self.template_leaves: Optional[List[Any]] = None
        if template_fn is not None:
            # returns None (one warning) when the sender's tree STRUCTURE
            # differs from the template's — index-aligned placement would
            # risk streaming leaves into the wrong buffers
            self.template_leaves = template_leaves_for(spec, template_fn(), logger)
        self.buf_lock = threading.Lock()
        self.stats_lock = threading.Lock()
        self.buffers: List[Optional[Any]] = [None] * len(spec.leaves)
        self.direct: List[bool] = [False] * len(spec.leaves)
        self.remaining: List[int] = [m.nbytes for m in spec.leaves]
        self.chunk_states = [_ChunkFetch(i) for i in range(num_chunks)]

    def _host_target(self, meta: Any, leaf_idx: int) -> Optional[Any]:
        """A host ndarray template leaf that can absorb this wire leaf
        lets the socket stream DIRECTLY into the resident buffer —
        zero wire-buffer alloc, the strongest in-place path."""
        if self.template_leaves is None or meta.kind != "array":
            return None
        t = self.template_leaves[leaf_idx]
        if can_absorb(t, meta.shape, meta.dtype, require_contiguous=True):
            return t
        return None

    def buffer_for(self, leaf_idx: int) -> Any:
        with self.buf_lock:
            if self.buffers[leaf_idx] is None:
                meta = self.spec.leaves[leaf_idx]
                if meta.kind == "array":
                    target = self._host_target(meta, leaf_idx)
                    if target is not None:
                        self.buffers[leaf_idx] = target
                        self.direct[leaf_idx] = True
                    else:
                        self.buffers[leaf_idx] = alloc_leaf(meta)
                else:
                    self.buffers[leaf_idx] = bytearray(meta.nbytes)
            return self.buffers[leaf_idx]

    def mark_written(self, leaf_idx: int, n: int) -> bool:
        """Credit ``n`` verified bytes; True when the leaf is complete
        (finalize on the calling thread, outside the lock)."""
        with self.buf_lock:
            self.remaining[leaf_idx] -= n
            if self.remaining[leaf_idx] < 0:
                raise ConnectionError(
                    f"leaf {leaf_idx}: overlapping/duplicate wire ranges"
                )
            return self.remaining[leaf_idx] == 0 and self.payloads[leaf_idx] is None

    def finish_leaf(self, leaf_idx: int) -> None:
        meta = self.spec.leaves[leaf_idx]
        arr = self.buffers[leaf_idx]
        if meta.kind == "array":
            if not self.direct[leaf_idx] and self.template_leaves is not None:
                # device template (device_put) or a mismatch
                # (warns "in-place receive degraded")
                arr = place_leaf_like(arr, self.template_leaves[leaf_idx], logger)
            self.payloads[leaf_idx] = arr
        else:
            self.payloads[leaf_idx] = bytes(arr)
