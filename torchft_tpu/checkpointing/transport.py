"""Checkpoint transport interface.

Mirror of the reference CheckpointTransport ABC
(torchft/checkpointing/transport.py:14-68): live-recovery state streaming
between replica groups. ``state_dict`` here is any JAX pytree.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from datetime import timedelta
from typing import Any, Generic, List, TypeVar

T = TypeVar("T")

__all__ = ["CheckpointTransport"]


class CheckpointTransport(ABC, Generic[T]):
    @abstractmethod
    def metadata(self) -> str:
        """Opaque string other replicas use to connect to this transport
        (fetched via the manager's checkpoint_metadata RPC)."""

    def configure(
        self,
        store_addr: str,
        replica_rank: int,
        replica_world_size: int,
        quorum_id: int = 0,
    ) -> None:
        """Per-quorum reconfiguration hook, called by the Manager right
        after it reconfigures its own process group (same membership, a
        distinct ``.../recovery/...`` store prefix).

        Default no-op: address-based transports (HTTP) don't care about
        quorum membership. ``PGTransport`` forwards this to its recovery
        process group so it rendezvouses with the new world — the host
        plane forbids mixing p2p and collective traffic on one PG
        generation (frame ordering), so unlike the reference's
        train_ddp.py:91-110 the recovery PG must be a SEPARATE instance,
        and this hook is what keeps it in lockstep with the quorum.
        """

    @abstractmethod
    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: "float | timedelta"
    ) -> None:
        """Serve/send ``state_dict`` for ``step`` to the given replica ranks."""

    def disallow_checkpoint(self) -> None:
        """Stop serving (the state is about to be mutated by the optimizer)."""

    @abstractmethod
    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: "float | timedelta"
    ) -> T:
        """Fetch the state for ``step`` from ``src_rank``."""

    def shutdown(self, wait: bool = True) -> None:
        """Tear down (terminal)."""
