"""Checkpoint transport interface.

Mirror of the reference CheckpointTransport ABC
(torchft/checkpointing/transport.py:14-68): live-recovery state streaming
between replica groups. ``state_dict`` here is any JAX pytree.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from datetime import timedelta
from typing import Any, Generic, List, TypeVar

T = TypeVar("T")

__all__ = ["CheckpointTransport"]


class CheckpointTransport(ABC, Generic[T]):
    @abstractmethod
    def metadata(self) -> str:
        """Opaque string other replicas use to connect to this transport
        (fetched via the manager's checkpoint_metadata RPC)."""

    @abstractmethod
    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: "float | timedelta"
    ) -> None:
        """Serve/send ``state_dict`` for ``step`` to the given replica ranks."""

    def disallow_checkpoint(self) -> None:
        """Stop serving (the state is about to be mutated by the optimizer)."""

    @abstractmethod
    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: "float | timedelta"
    ) -> T:
        """Fetch the state for ``step`` from ``src_rank``."""

    def shutdown(self, wait: bool = True) -> None:
        """Tear down (terminal)."""
