"""Checkpoint transport interface + shared streaming helpers.

Mirror of the reference CheckpointTransport ABC
(torchft/checkpointing/transport.py:14-68): live-recovery state streaming
between replica groups. ``state_dict`` here is any JAX pytree.

The streaming helpers are the shared half of the pipelined heal path used
by both concrete transports:

- ``plan_wire_ranges`` chunks a flattened state into byte ranges
  ``(leaf_idx, offset, nbytes)`` — BYTE-granular, so a single multi-GB
  leaf (the common shape for a fused parameter buffer) still splits into
  multiple wire chunks instead of store-and-forwarding as one blob;
- ``pipelined`` overlaps the wire transfer of chunk ``i+1`` with the
  finish work (device placement / reassembly) of chunk ``i``;
- ``StreamTimings`` / ``ChunkStat`` carry per-chunk throughput back to
  the Manager (``heal_chunks`` / ``heal_mb_per_s`` timings).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from datetime import timedelta
from typing import Any, Callable, Generic, Iterable, List, Optional, Tuple, TypeVar

T = TypeVar("T")
U = TypeVar("U")

__all__ = [
    "CheckpointTransport",
    "ChunkStat",
    "StreamTimings",
    "pipelined",
    "plan_wire_ranges",
    "stream_chunk_bytes",
]

STREAM_CHUNK_BYTES_ENV = "TORCHFT_STREAM_CHUNK_BYTES"
DEFAULT_STREAM_CHUNK_BYTES = 32 << 20  # 32 MiB


def stream_chunk_bytes() -> int:
    """Target wire-chunk size for streamed heal transfers, overridable via
    ``TORCHFT_STREAM_CHUNK_BYTES`` (values < 1 fall back to the default —
    a zero chunk size would loop forever in ``plan_wire_ranges``)."""
    raw = os.environ.get(STREAM_CHUNK_BYTES_ENV, "")
    try:
        val = int(raw)
    except ValueError:
        return DEFAULT_STREAM_CHUNK_BYTES
    return val if val >= 1 else DEFAULT_STREAM_CHUNK_BYTES


def plan_wire_ranges(
    leaf_nbytes: List[int], chunk_bytes: int
) -> List[List[Tuple[int, int, int]]]:
    """Plan wire chunks over flattened leaves as byte ranges.

    Returns a list of chunks, each a list of ``(leaf_idx, offset, nbytes)``
    ranges summing to at most ``chunk_bytes`` (except that every range is
    non-empty, so a chunk always makes progress). Unlike leaf-granularity
    ``split_chunks``, a leaf larger than ``chunk_bytes`` is split across
    chunks — that is what lets a single huge parameter buffer pipeline.
    Deterministic in its inputs, so sender and receiver can independently
    derive the same plan. Zero-byte leaves ride along with the next chunk
    (offset 0, nbytes 0) so every leaf appears in exactly one range."""
    if chunk_bytes < 1:
        raise ValueError(f"chunk_bytes must be >= 1, got {chunk_bytes}")
    chunks: List[List[Tuple[int, int, int]]] = []
    cur: List[Tuple[int, int, int]] = []
    cur_bytes = 0
    for idx, total in enumerate(leaf_nbytes):
        if total == 0:
            cur.append((idx, 0, 0))
            continue
        off = 0
        while off < total:
            take = min(total - off, chunk_bytes - cur_bytes)
            if take == 0:
                chunks.append(cur)
                cur, cur_bytes = [], 0
                continue
            cur.append((idx, off, take))
            off += take
            cur_bytes += take
            if cur_bytes >= chunk_bytes:
                chunks.append(cur)
                cur, cur_bytes = [], 0
    if cur:
        chunks.append(cur)
    if not chunks:
        chunks.append([])
    return chunks


@dataclass
class ChunkStat:
    """Wire timing of one streamed chunk (transfer only, not finish)."""

    nbytes: int
    transfer_s: float


@dataclass
class StreamTimings:
    """Aggregate stats of the last streamed recv, surfaced to the Manager
    via ``CheckpointTransport.last_recv_timings``."""

    total_bytes: int = 0
    total_s: float = 0.0
    chunks: List[ChunkStat] = field(default_factory=list)
    # resilience counters of the recv (multi-source transports only):
    retries: int = 0  # same-source stall resumes / refetches
    failovers: int = 0  # mid-heal switches to a fallback source
    crc_failures: int = 0  # chunks refetched after a crc32 mismatch

    @property
    def num_chunks(self) -> int:
        return len(self.chunks)

    @property
    def mb_per_s(self) -> float:
        if self.total_s <= 0:
            return 0.0
        return (self.total_bytes / (1 << 20)) / self.total_s


def pipelined(
    items: Iterable[T],
    transfer: Callable[[T], U],
    finish: Callable[[U], None],
    depth: int = 2,
    timings: Optional[StreamTimings] = None,
    size_of: Optional[Callable[[U], int]] = None,
) -> None:
    """Run ``transfer`` over ``items`` on a worker thread while ``finish``
    consumes completed results on the calling thread — chunk ``i+1`` is on
    the wire while chunk ``i`` is being placed. ``depth`` bounds how many
    transferred-but-unfinished results may buffer (memory bound). A failure
    on either side aborts the stream: the worker stops at the next queue
    put, and the first exception (transfer wins over finish) propagates."""
    q: "queue.Queue[Tuple[bool, Any]]" = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()
    t_start = time.perf_counter()

    def producer() -> None:
        try:
            for item in items:
                if stop.is_set():
                    return
                t0 = time.perf_counter()
                out = transfer(item)
                dt = time.perf_counter() - t0
                if timings is not None:
                    nb = size_of(out) if size_of is not None else 0
                    timings.chunks.append(ChunkStat(nbytes=nb, transfer_s=dt))
                    timings.total_bytes += nb
                q.put((True, out))
            q.put((True, _DONE))
        except BaseException as e:  # noqa: BLE001 — must unblock the consumer
            q.put((False, e))

    worker = threading.Thread(
        target=producer, name="torchft_stream", daemon=True
    )
    worker.start()
    try:
        while True:
            ok, payload = q.get()
            if not ok:
                raise payload
            if payload is _DONE:
                break
            finish(payload)
    except BaseException:
        stop.set()
        # drain one slot so a blocked producer put() can observe stop
        try:
            q.get_nowait()
        except queue.Empty:
            pass
        raise
    finally:
        worker.join(timeout=60)
        if timings is not None:
            timings.total_s = time.perf_counter() - t_start


class _Done:
    __slots__ = ()


_DONE = _Done()


class CheckpointTransport(ABC, Generic[T]):
    @abstractmethod
    def metadata(self) -> str:
        """Opaque string other replicas use to connect to this transport
        (fetched via the manager's checkpoint_metadata RPC)."""

    def configure(
        self,
        store_addr: str,
        replica_rank: int,
        replica_world_size: int,
        quorum_id: int = 0,
    ) -> None:
        """Per-quorum reconfiguration hook, called by the Manager right
        after it reconfigures its own process group (same membership, a
        distinct ``.../recovery/...`` store prefix).

        Default no-op: address-based transports (HTTP) don't care about
        quorum membership. ``PGTransport`` forwards this to its recovery
        process group so it rendezvouses with the new world — the host
        plane forbids mixing p2p and collective traffic on one PG
        generation (frame ordering), so unlike the reference's
        train_ddp.py:91-110 the recovery PG must be a SEPARATE instance,
        and this hook is what keeps it in lockstep with the quorum.
        """

    @abstractmethod
    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: "float | timedelta"
    ) -> None:
        """Serve/send ``state_dict`` for ``step`` to the given replica ranks."""

    def disallow_checkpoint(self) -> None:
        """Stop serving (the state is about to be mutated by the optimizer)."""

    @abstractmethod
    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: "float | timedelta"
    ) -> T:
        """Fetch the state for ``step`` from ``src_rank``."""

    # Pull-based transports that can fetch the same step from any up-to-date
    # peer set this True and implement recv_checkpoint_multi; push-based
    # transports (PGTransport: only the assigned source is sending) cannot
    # fail over without sender-side coordination and must keep it False so
    # the Manager never blocks on a fallback peer that will never send.
    supports_multi_source: bool = False

    def recv_checkpoint_multi(
        self,
        sources: List[Tuple[str, Callable[[], str]]],
        step: int,
        timeout: "float | timedelta",
        on_event: Optional[Callable[..., None]] = None,
    ) -> T:
        """Fetch the state for ``step`` from an ordered list of candidate
        sources, failing over mid-transfer when one dies.

        ``sources`` is ``[(label, metadata_fn), ...]`` — ``metadata_fn``
        resolves the peer's transport metadata lazily (typically a
        ``_checkpoint_metadata`` RPC) so an unreachable fallback costs
        nothing unless it is actually tried. ``on_event(kind, **fields)``
        receives ``heal_retry`` / ``heal_failover`` / ``chunk_crc_failure``
        notifications as they happen."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support multi-source receive"
        )

    def last_recv_timings(self) -> Optional[StreamTimings]:
        """Chunk-stream stats of the most recent ``recv_checkpoint`` (None
        when the transport doesn't stream or hasn't received yet). The
        Manager folds these into its ``timings()`` as ``heal_chunks`` /
        ``heal_mb_per_s``."""
        return getattr(self, "_last_recv_timings", None)

    def shutdown(self, wait: bool = True) -> None:
        """Tear down (terminal)."""
