"""Systematic erasure codec for the redundancy plane (pure numpy).

``k`` data shards + ``m`` parity shards over GF(256): any ``k`` of the
``k + m`` shards reconstruct the original payload bitwise. The code is
**systematic** — the first ``k`` shards are verbatim slices of the
payload — so the common reconstruct case (all data-shard holders alive)
is a concatenation with zero field arithmetic, and parity math only runs
for the shards that are actually missing or corrupt.

Construction is the classic Vandermonde-then-normalize generator (the
same scheme as Backblaze's JavaReedSolomon and torchsnapshot-style RS
codecs): build a ``(k+m) x k`` Vandermonde matrix over distinct field
points, right-multiply by the inverse of its top ``k x k`` block so the
data rows become the identity. Any ``k`` rows of a Vandermonde matrix
with distinct points are themselves a square Vandermonde matrix —
invertible — and right-multiplication by a fixed invertible matrix
preserves that, so **every** ``k``-subset of shards decodes.

``m == 1`` degenerates to XOR parity (the normalized single parity row
is all-ones), which the hot paths exploit implicitly: one missing shard
costs ``k`` table-gathered multiplies either way, and for ``m == 1``
those coefficients are 1 so the gather is the identity lookup.

Payloads are padded to ``k * shard_len``; the true length travels in the
shard directory entry (``data_len``) and is restored on decode. All
arithmetic is vectorized through a lazily-built 256x256 GF(256) product
table (64 KiB), so per-shard work is numpy fancy-indexing gathers + XOR
reductions — no Python-level byte loops.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "encode_shards",
    "decode_shards",
    "encoding_matrix",
    "shard_crc",
    "shard_length",
]

_GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1, the AES-adjacent standard


def _build_tables() -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _GF_POLY
    exp[255:510] = exp[0:255]  # wraparound so exp[a+b] never needs a mod
    # full product table: MUL[a, b] = a * b in GF(256). 64 KiB, built once.
    a = np.arange(256, dtype=np.int32)
    la, lb = np.meshgrid(log[a], log[a], indexing="ij")
    mul = exp[(la + lb) % 255].astype(np.uint8)
    mul[0, :] = 0
    mul[:, 0] = 0
    return exp, log, mul


_EXP: Optional[np.ndarray] = None
_LOG: Optional[np.ndarray] = None
_MUL: Optional[np.ndarray] = None


def _tables() -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    global _EXP, _LOG, _MUL
    if _MUL is None:
        _EXP, _LOG, _MUL = _build_tables()
    return _EXP, _LOG, _MUL  # type: ignore[return-value]


def _gf_mul_scalar(a: int, b: int) -> int:
    _, _, mul = _tables()
    return int(mul[a, b])


def _gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of 0")
    exp, log, _ = _tables()
    return int(exp[255 - int(log[a])])


def _gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(256) for small coefficient matrices."""
    _, _, mul = _tables()
    out = np.zeros((a.shape[0], b.shape[1]), dtype=np.uint8)
    for i in range(a.shape[0]):
        acc = np.zeros(b.shape[1], dtype=np.uint8)
        for t in range(a.shape[1]):
            acc ^= mul[a[i, t]][b[t]]
        out[i] = acc
    return out


def _gf_matinv(mat: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inversion over GF(256) (coefficient-sized: k <= 255)."""
    _, _, mul = _tables()
    n = mat.shape[0]
    aug = np.concatenate(
        [mat.astype(np.uint8).copy(), np.eye(n, dtype=np.uint8)], axis=1
    )
    for col in range(n):
        pivot = next(
            (r for r in range(col, n) if aug[r, col] != 0), None
        )
        if pivot is None:
            raise ValueError("singular matrix over GF(256)")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv_p = _gf_inv(int(aug[col, col]))
        aug[col] = mul[inv_p][aug[col]]
        for r in range(n):
            if r != col and aug[r, col] != 0:
                aug[r] ^= mul[int(aug[r, col])][aug[col]]
    return aug[:, n:]


def encoding_matrix(k: int, m: int) -> np.ndarray:
    """The systematic ``(k+m) x k`` generator: identity on top, parity
    coefficient rows below. Deterministic in (k, m) — encoder and every
    decoder derive the same matrix independently."""
    if k < 1 or m < 0 or k + m > 255:
        raise ValueError(f"unsupported erasure geometry k={k} m={m}")
    _tables()
    if m == 1:
        # RAID-5 degenerate case: identity + all-ones XOR row. Any k rows
        # are either the identity or k-1 unit rows + the ones row — both
        # invertible — and encode/repair needs no field multiplies.
        return np.concatenate(
            [np.eye(k, dtype=np.uint8), np.ones((1, k), dtype=np.uint8)]
        )
    # Vandermonde over the distinct points 0..k+m-1: row r = [r^0 .. r^(k-1)]
    # (0^0 == 1 by convention, so row 0 is [1, 0, 0, ...])
    vand = np.zeros((k + m, k), dtype=np.uint8)
    for r in range(k + m):
        acc = 1
        for c in range(k):
            vand[r, c] = acc
            acc = _gf_mul_scalar(acc, r)
    top_inv = _gf_matinv(vand[:k])
    gen = _gf_matmul(vand, top_inv)
    # normalization guarantee: the data block is exactly the identity
    gen[:k] = np.eye(k, dtype=np.uint8)
    return gen


def shard_length(data_len: int, k: int) -> int:
    """Per-shard byte length for a payload of ``data_len`` (ceil-div,
    min 1 so zero-length payloads still produce addressable shards)."""
    return max(1, (int(data_len) + k - 1) // k)


def encode_shards(payload, k: int, m: int) -> List[bytes]:
    """Encode ``payload`` (bytes-like) into ``k + m`` shards.

    Shards ``0..k-1`` are verbatim payload slices (zero-padded tail);
    shards ``k..k+m-1`` are GF(256) parity. Bitwise round-trip with
    :func:`decode_shards` is pinned by tests/test_erasure.py.
    """
    _, _, mul = _tables()
    data = np.frombuffer(memoryview(payload), dtype=np.uint8)
    slen = shard_length(data.nbytes, k)
    padded = np.zeros(k * slen, dtype=np.uint8)
    padded[: data.nbytes] = data
    rows = padded.reshape(k, slen)
    gen = encoding_matrix(k, m)
    shards: List[bytes] = [rows[i].tobytes() for i in range(k)]
    for p in range(m):
        coefs = gen[k + p]
        acc = np.zeros(slen, dtype=np.uint8)
        for i in range(k):
            c = int(coefs[i])
            if c == 0:
                continue
            acc ^= rows[i] if c == 1 else mul[c][rows[i]]
        shards.append(acc.tobytes())
    return shards


def decode_shards(
    shards: Sequence[Optional[bytes]], k: int, m: int, data_len: int
) -> bytes:
    """Reconstruct the original payload from any ``k`` present shards.

    ``shards`` is the full ``k + m`` slot list with ``None`` for
    missing/corrupt entries (callers drop a shard by CRC mismatch before
    decoding). Raises ``ValueError`` when fewer than ``k`` survive.
    """
    _, _, mul = _tables()
    if len(shards) != k + m:
        raise ValueError(f"expected {k + m} shard slots, got {len(shards)}")
    present = [i for i, s in enumerate(shards) if s is not None]
    if len(present) < k:
        raise ValueError(
            f"unrecoverable: only {len(present)} of {k + m} shards present "
            f"(need {k})"
        )
    slen = shard_length(data_len, k)
    use = present[:k]
    if use == list(range(k)):
        # systematic fast path: all data shards arrived — pure concat
        out = np.concatenate(
            [np.frombuffer(shards[i], dtype=np.uint8) for i in range(k)]
        )
        return out[:data_len].tobytes()
    gen = encoding_matrix(k, m)
    sub = gen[use]  # k x k, invertible by the Vandermonde property
    dec = _gf_matinv(sub)
    rows = [
        np.frombuffer(shards[i], dtype=np.uint8) for i in use
    ]
    for r in rows:
        if r.nbytes != slen:
            raise ValueError(
                f"shard length mismatch: got {r.nbytes}, expected {slen}"
            )
    out = np.empty(k * slen, dtype=np.uint8)
    for d in range(k):
        coefs = dec[d]
        acc = np.zeros(slen, dtype=np.uint8)
        for t in range(k):
            c = int(coefs[t])
            if c == 0:
                continue
            acc ^= rows[t] if c == 1 else mul[c][rows[t]]
        out[d * slen : (d + 1) * slen] = acc
    return out[:data_len].tobytes()


def shard_crc(shard) -> int:
    """crc32 over a shard body — the same checksum family the ranged
    HTTP transport trailers use, so corrupt shards are detected before
    they reach the decoder."""
    return zlib.crc32(memoryview(shard)) & 0xFFFFFFFF
