from torchft_tpu.checkpointing._rwlock import RWLock

__all__ = ["RWLock"]
