from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.checkpointing.durable import DurableCheckpointer
from torchft_tpu.checkpointing.erasure import (
    decode_shards,
    encode_shards,
    shard_crc,
    shard_length,
)
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.checkpointing.pg_transport import PGTransport
from torchft_tpu.checkpointing.transport import CheckpointTransport

__all__ = [
    "RWLock",
    "CheckpointTransport",
    "DurableCheckpointer",
    "HTTPTransport",
    "PGTransport",
    "decode_shards",
    "encode_shards",
    "shard_crc",
    "shard_length",
]
