"""Multi-replica-group job launcher (reference: torchft/torchx.py:17-89).

The reference exposes a TorchX component that materialises one
``torchrun``-managed role per replica group with the env contract
``REPLICA_GROUP_ID`` / ``NUM_REPLICA_GROUPS`` / ``TORCHFT_LIGHTHOUSE``.
This launcher provides the same contract for local/multi-process TPU jobs —
and additionally *supervises*: failed replica groups are restarted up to
``--max-restarts`` times, which is the piece torchelastic provided in the
reference stack (a replica group that dies rejoins the quorum and live-heals
from a peer).

CLI::

    python -m torchft_tpu.launcher train.py --replica-groups 2 \
        --workers-per-replica 1 --max-restarts 3 -- --train-arg ...

or programmatic: ``launch_replica_groups(cmd, num_groups, ...)``.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from torchft_tpu.coordination import LighthouseServer

logger = logging.getLogger(__name__)

__all__ = ["ReplicaGroupSpec", "launch_replica_groups", "main"]

LIGHTHOUSE_ENV = "TORCHFT_LIGHTHOUSE"
REPLICA_GROUP_ID_ENV = "REPLICA_GROUP_ID"
NUM_REPLICA_GROUPS_ENV = "NUM_REPLICA_GROUPS"
GROUP_RANK_ENV = "GROUP_RANK"
GROUP_WORLD_SIZE_ENV = "GROUP_WORLD_SIZE"


@dataclass
class ReplicaGroupSpec:
    """One replica group's process set (reference role, torchx.py:55-85)."""

    cmd: List[str]
    replica_group_id: int
    num_replica_groups: int
    workers_per_replica: int = 1
    env: Dict[str, str] = field(default_factory=dict)

    def spawn(self, lighthouse_addr: str) -> List[subprocess.Popen]:
        procs = []
        for group_rank in range(self.workers_per_replica):
            env = {
                **os.environ,
                **self.env,
                LIGHTHOUSE_ENV: lighthouse_addr,
                REPLICA_GROUP_ID_ENV: str(self.replica_group_id),
                NUM_REPLICA_GROUPS_ENV: str(self.num_replica_groups),
                GROUP_RANK_ENV: str(group_rank),
                GROUP_WORLD_SIZE_ENV: str(self.workers_per_replica),
            }
            procs.append(subprocess.Popen(self.cmd, env=env))
        return procs


def launch_replica_groups(
    cmd: List[str],
    num_groups: int,
    workers_per_replica: int = 1,
    lighthouse_addr: Optional[str] = None,
    min_replicas: Optional[int] = None,
    max_restarts: int = 0,
    poll_interval: float = 1.0,
) -> int:
    """Run ``cmd`` as ``num_groups`` replica groups; supervise + restart.

    Returns the exit code: 0 iff every group eventually exited cleanly.
    Starts an in-process lighthouse when ``lighthouse_addr`` is None.
    """
    own_lighthouse = None
    if lighthouse_addr is None:
        own_lighthouse = LighthouseServer(
            bind="0.0.0.0:0",
            min_replicas=min_replicas if min_replicas is not None else num_groups,
        )
        lighthouse_addr = own_lighthouse.address()
        logger.info("launcher lighthouse at %s", lighthouse_addr)

    specs = [
        ReplicaGroupSpec(
            cmd=cmd,
            replica_group_id=i,
            num_replica_groups=num_groups,
            workers_per_replica=workers_per_replica,
        )
        for i in range(num_groups)
    ]
    groups: List[List[subprocess.Popen]] = [s.spawn(lighthouse_addr) for s in specs]
    restarts = [0] * num_groups
    done = [False] * num_groups
    failed = False

    stop = threading.Event()
    prev_handlers = {}
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            prev_handlers[sig] = signal.signal(sig, lambda *_: stop.set())
        except ValueError:  # not on the main thread (tests)
            pass

    try:
        while not stop.is_set() and not all(done):
            time.sleep(poll_interval)
            for i, procs in enumerate(groups):
                if done[i]:
                    continue
                codes = [p.poll() for p in procs]
                if all(c == 0 for c in codes):
                    done[i] = True
                    logger.info("replica group %d finished", i)
                elif any(c is not None and c != 0 for c in codes):
                    # kill stragglers of the dead group, then restart or fail
                    for p in procs:
                        if p.poll() is None:
                            p.terminate()
                    for p in procs:
                        try:
                            p.wait(timeout=30)
                        except subprocess.TimeoutExpired:
                            # a straggler trapping SIGTERM must not crash
                            # the supervisor; escalate like the final
                            # teardown does
                            p.kill()
                            try:
                                p.wait(timeout=30)
                            except subprocess.TimeoutExpired:
                                # even SIGKILL can stall on D-state I/O;
                                # carry on supervising rather than dying
                                logger.warning(
                                    "worker pid %s unkillable; continuing",
                                    p.pid,
                                )
                    if restarts[i] < max_restarts:
                        restarts[i] += 1
                        logger.warning(
                            "replica group %d died (codes=%s); restart %d/%d",
                            i, codes, restarts[i], max_restarts,
                        )
                        groups[i] = specs[i].spawn(lighthouse_addr)
                    else:
                        logger.error(
                            "replica group %d died (codes=%s); out of restarts",
                            i, codes,
                        )
                        done[i] = True
                        failed = True
    finally:
        for procs in groups:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
        for procs in groups:
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
        if own_lighthouse is not None:
            own_lighthouse.shutdown()
        for sig, h in prev_handlers.items():
            signal.signal(sig, h)

    return 1 if (failed or stop.is_set()) else 0


def main(argv: "list[str] | None" = None) -> None:
    parser = argparse.ArgumentParser(prog="torchft_tpu_launcher", description=__doc__)
    parser.add_argument("script", help="worker script (run with this python)")
    parser.add_argument("--replica-groups", type=int, default=2)
    parser.add_argument("--workers-per-replica", type=int, default=1)
    parser.add_argument("--lighthouse", default=None,
                        help="existing lighthouse addr; else start one")
    parser.add_argument("--min-replicas", type=int, default=None)
    parser.add_argument("--max-restarts", type=int, default=0)

    # everything after a literal `--` goes verbatim to the worker script
    if argv is None:
        argv = sys.argv[1:]
    if "--" in argv:
        split = argv.index("--")
        argv, worker_args = argv[:split], argv[split + 1:]
    else:
        worker_args = []
    ns = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    code = launch_replica_groups(
        [sys.executable, ns.script, *worker_args],
        num_groups=ns.replica_groups,
        workers_per_replica=ns.workers_per_replica,
        lighthouse_addr=ns.lighthouse,
        min_replicas=ns.min_replicas,
        max_restarts=ns.max_restarts,
    )
    sys.exit(code)


if __name__ == "__main__":
    main()
