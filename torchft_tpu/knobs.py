"""Central ``TORCHFT_*`` knob registry: the single source of truth every
env-var contract check hangs off.

Every environment variable the package reads is declared here once, with
its type, default, the doc section that explains it, and the doctor check
(if any) that validates it on a live host. The fleetlint env-contract
checker (``torchft_tpu/analysis/env_contract.py``) cross-checks this
registry three ways:

- a ``TORCHFT_*`` read in code that is **not** registered here is an
  *unregistered read* (new knobs must land with a registration);
- a registered knob that is never read anywhere is a *dead knob*;
- a registered knob whose name does not appear in ``docs/api.md`` is
  *undocumented*, and one with ``doctor=None`` is *un-doctored* (accepted
  ones live in the committed fleetlint baseline with a justification).

Runtime code funnels reads through :func:`env_raw` (or the typed
wrappers) so an unregistered name fails loudly in tests instead of
becoming a silent tribal-knowledge knob.
"""

from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, Optional, TypeVar

T = TypeVar("T")


@dataclass(frozen=True)
class Knob:
    """One registered environment variable."""

    name: str  # full TORCHFT_* env name
    type: str  # "str" | "int" | "float" | "bool" | "enum(...)"
    default: str  # human-readable default ("" = unset)
    doc: str  # docs anchor, e.g. "api.md#environment-contract"
    doctor: Optional[str]  # doctor check name validating it, or None
    summary: str  # one-line operator-facing description


def _k(
    name: str,
    type: str,
    default: str,
    doc: str,
    doctor: Optional[str],
    summary: str,
) -> Knob:
    return Knob(name, type, default, doc, doctor, summary)


REGISTRY: Dict[str, Knob] = {
    k.name: k
    for k in [
        # ------------------------------------------------- control plane
        _k("TORCHFT_LIGHTHOUSE", "str", "", "api.md#manager", "aggregator",
           "Root lighthouse address (host:port) managers coordinate through."),
        _k("TORCHFT_LIGHTHOUSE_AGGREGATOR", "str", "", "operations.md#running-a-fleet",
           "aggregator",
           "Pod-level lighthouse aggregator address; beats fail over to the root."),
        _k("TORCHFT_MANAGER_PORT", "int", "0", "api.md#manager", "tuning-env",
           "Bind port for the group-leader ManagerServer (0 = ephemeral)."),
        _k("TORCHFT_TIMEOUT_SEC", "float", "60", "api.md#manager", "retry-env",
           "Default control-plane RPC deadline in seconds."),
        _k("TORCHFT_QUORUM_TIMEOUT_SEC", "float", "60", "api.md#manager", "retry-env",
           "Quorum formation deadline; retry backoff budgets are ordered below it."),
        _k("TORCHFT_CONNECT_TIMEOUT_SEC", "float", "10", "api.md#manager", "tuning-env",
           "TCP connect deadline for control-plane clients."),
        _k("TORCHFT_QUORUM_RETRIES", "int", "0", "api.md#manager", "tuning-env",
           "Consecutive quorum failures tolerated before the manager raises."),
        _k("TORCHFT_HEARTBEAT_INTERVAL_MS", "float", "100", "api.md#manager",
           "health-env",
           "Manager heartbeat cadence; health probation windows are sized against it."),
        _k("TORCHFT_HOST", "str", "127.0.0.1", "api.md#process-groups", "tuning-env",
           "Hostname the XLA store/transport advertises (multi-host fleets)."),
        # --------------------------------------------------- data plane
        _k("TORCHFT_BUCKET_CAP_MB", "float", "32", "performance.md#bucketing", "tuning-env",
           "Allreduce flat-bucket cap in MB; 0 disables bucketing."),
        _k("TORCHFT_STREAM_BUCKETS", "bool", "1", "performance.md#streaming",
           "compress-env",
           "Per-bucket streamed allreduce pipeline (off = serial collectives)."),
        _k("TORCHFT_COMPRESS", "enum(off|fp8|int8)", "off",
           "performance.md#compressed-collectives", "compress-env",
           "Wire codec for streamed buckets, with per-bucket error feedback."),
        _k("TORCHFT_STREAM_CHUNK_BYTES", "int", "1048576", "api.md#checkpointing",
           "tuning-env",
           "Heal/checkpoint transport chunk size in bytes."),
        _k("TORCHFT_USE_BUCKETIZATION", "bool", "0", "performance.md#bucketing", "tuning-env",
           "LocalSGD/DiLoCo fragment bucketization toggle."),
        # -------------------------------------------------- retry plane
        _k("TORCHFT_RETRY_MAX_ATTEMPTS", "int", "3", "operations.md#failure-modes",
           "retry-env", "Control-plane RPC attempts before RetryBudgetExhausted."),
        _k("TORCHFT_RETRY_BASE_S", "float", "0.1", "operations.md#failure-modes",
           "retry-env", "First retry backoff in seconds (doubles per attempt)."),
        _k("TORCHFT_RETRY_MAX_BACKOFF_S", "float", "5", "operations.md#failure-modes",
           "retry-env", "Backoff ceiling; must stay below the quorum timeout."),
        _k("TORCHFT_RETRY_JITTER", "float", "0.5", "operations.md#failure-modes",
           "retry-env", "Backoff jitter fraction decorrelating retry herds."),
        # ------------------------------------------------- health plane
        _k("TORCHFT_HEALTH_MODE", "enum(off|observe|eject)", "observe",
           "operations.md#straggler-management", "health-env",
           "Healthwatch escalation mode."),
        _k("TORCHFT_HEALTH_WINDOW", "int", "32",
           "operations.md#straggler-management", "health-env",
           "Rolling telemetry window per replica."),
        _k("TORCHFT_HEALTH_MIN_SAMPLES", "int", "5",
           "operations.md#straggler-management", "health-env",
           "Warmup samples before a replica is scored."),
        _k("TORCHFT_HEALTH_WARN_Z", "float", "3.0",
           "operations.md#straggler-management", "health-env",
           "Modified z-score that marks a straggler warn."),
        _k("TORCHFT_HEALTH_EJECT_Z", "float", "6.0",
           "operations.md#straggler-management", "health-env",
           "Modified z-score that counts an eject strike."),
        _k("TORCHFT_HEALTH_EJECT_STEPS", "int", "3",
           "operations.md#straggler-management", "health-env",
           "Consecutive strikes before proactive ejection."),
        _k("TORCHFT_HEALTH_PROBATION_MS", "int", "10000",
           "operations.md#straggler-management", "health-env",
           "Probationary readmission window after an eject."),
        _k("TORCHFT_HEALTH_PROBE_OK", "int", "3",
           "operations.md#straggler-management", "health-env",
           "Clean probation samples required for readmission."),
        _k("TORCHFT_HEALTH_REL_FLOOR", "float", "0.05",
           "operations.md#straggler-management", "health-env",
           "Relative slowdown floor below which z-scores never escalate."),
        # ------------------------------------------------ observability
        _k("TORCHFT_TRACE", "bool", "1", "observability.md#span-taxonomy",
           "trace-env", "Span recorder on/off (on by default, <1% overhead)."),
        _k("TORCHFT_TRACE_BUFFER", "int", "4096", "observability.md#span-taxonomy",
           "trace-env", "Span ring capacity (floor 16; overflow is counted)."),
        _k("TORCHFT_TRACE_SAMPLE", "float", "1.0", "observability.md#span-taxonomy",
           "trace-env", "Fraction of steps traced (deterministic by step hash)."),
        _k("TORCHFT_TRACE_DIR", "str", "", "observability.md#span-taxonomy",
           "trace-env", "Trace dump directory (empty = beside flight-recorder dumps)."),
        _k("TORCHFT_METRICS_PORT", "int", "", "observability.md#metrics-reference",
           "tuning-env", "Manager-side Prometheus /metrics port (unset = not served)."),
        _k("TORCHFT_METRICS_PER_REPLICA_LIMIT", "int", "64",
           "observability.md#metrics-reference", "tuning-env",
           "Per-replica series cap on the lighthouse /metrics exporter."),
        _k("TORCHFT_FR_BASE_PATH", "str", "", "api.md#observability", "tuning-env",
           "Flight-recorder dump directory (empty = temp dir)."),
        _k("TORCHFT_FR_CAPACITY", "int", "512", "api.md#observability", "tuning-env",
           "Flight-recorder ring capacity in events."),
        _k("TORCHFT_USE_OTEL", "bool", "0", "api.md#observability", "tuning-env",
           "Mirror structured events to an OTLP exporter when available."),
        _k("TORCHFT_OTEL_RESOURCE_ATTRIBUTES_JSON", "str", "", "api.md#observability",
           "tuning-env", "Extra OTLP resource attributes as a JSON object."),
        # ------------------------------------------------ serving plane
        _k("TORCHFT_SERVE_REGISTRY", "str", "", "serving.md#env-contract",
           "serve-env", "Snapshot-registry base URL; empty disables the plane."),
        _k("TORCHFT_SERVE_MAX_LAG", "int", "8", "serving.md#env-contract",
           "serve-env", "Delta-ring depth; workers further behind full-pull."),
        _k("TORCHFT_SERVE_COMPRESS", "enum(off|fp8|int8)", "fp8",
           "serving.md#env-contract", "serve-env",
           "Delta wire codec for published snapshots."),
        _k("TORCHFT_SERVE_POLL_S", "float", "0.05", "serving.md#env-contract",
           "serve-env", "Worker poll interval in seconds."),
        _k("TORCHFT_SERVE_DRAIN_ON", "enum(warn|eject)", "warn",
           "serving.md#env-contract", "serve-env",
           "Health state that drains a source from serve rotation."),
        _k("TORCHFT_SERVE_PORT", "int", "0", "serving.md#env-contract",
           "serve-env", "Inference worker HTTP port (0 = ephemeral)."),
        _k("TORCHFT_SERVE_TIMEOUT_S", "float", "15", "serving.md#env-contract",
           "serve-env", "Per-pull / per-RPC deadline on the serving plane."),
        # --------------------------------------------- redundancy plane
        _k("TORCHFT_REDUNDANCY_K", "int", "0", "operations.md#fast-recovery",
           "redundancy-env", "Erasure data shards per generation; 0 = plane off."),
        _k("TORCHFT_REDUNDANCY_M", "int", "1", "operations.md#fast-recovery",
           "redundancy-env", "Erasure parity shards per generation."),
        _k("TORCHFT_REDUNDANCY_DIRECTORY", "str", "", "operations.md#fast-recovery",
           "redundancy-env", "ShardDirectory base URL (lighthouse --redundancy-directory)."),
        _k("TORCHFT_REDUNDANCY_INTERVAL", "int", "1", "operations.md#fast-recovery",
           "redundancy-env", "Stage shards every N committed generations."),
        _k("TORCHFT_REDUNDANCY_TIMEOUT_S", "float", "15", "operations.md#fast-recovery",
           "redundancy-env", "Per shard-RPC deadline."),
        _k("TORCHFT_REDUNDANCY_RETAIN", "int", "2", "operations.md#fast-recovery",
           "redundancy-env", "Shard generations retained per owner in each store."),
        _k("TORCHFT_POD", "str", "", "operations.md#running-a-fleet", "tuning-env",
           "Placement pod identity (defaults to the aggregator-derived pod)."),
        # ------------------------------------------------------ policy plane
        _k("TORCHFT_POLICY", "enum(off|observe|enforce)", "off",
           "operations.md#adaptive-policies", "policy-env",
           "Adaptive policy engine mode: off = byte-identical legacy"
           " behavior, observe = log would-be actions, enforce = apply."),
        _k("TORCHFT_POLICY_SPEC", "str", "builtin",
           "operations.md#adaptive-policies", "policy-env",
           "PolicySpec source: 'builtin' or a path to a PolicySpec JSON."),
        _k("TORCHFT_POLICY_INTERVAL_S", "float", "5",
           "operations.md#adaptive-policies", "policy-env",
           "Engine evaluation cadence in seconds (fold + rule pass)."),
        _k("TORCHFT_POLICY_WINDOW_S", "float", "300",
           "operations.md#adaptive-policies", "policy-env",
           "Rolling window the fleet signals (MTBF, churn, ...) cover."),
        _k("TORCHFT_POLICY_RING", "int", "4096",
           "operations.md#adaptive-policies", "policy-env",
           "Lighthouse in-memory event-ring capacity feeding the engine."),
        _k("TORCHFT_SYNC_EVERY", "int", "0",
           "operations.md#adaptive-policies", "policy-env",
           "LocalSGD/DiLoCo sync_every override (> 0 wins over the"
           " constructor argument; the policy plane retargets it live)."),
        # ---------------------------------------------------- degrade plane
        _k("TORCHFT_DEGRADE", "enum(off|on)", "off",
           "operations.md#degraded-replicas", "degrade-env",
           "Degrade-in-place: shrink TP/PP onto surviving chips instead of"
           " leaving the quorum when a group member dies."),
        _k("TORCHFT_DEGRADE_MIN_DEGREE", "int", "1",
           "operations.md#degraded-replicas", "degrade-env",
           "Smallest surviving group degree worth resharding onto; below it"
           " the replica falls back to the classic leave-heal-rejoin path."),
        _k("TORCHFT_DEGRADE_RESTORE", "enum(auto|manual)", "auto",
           "operations.md#degraded-replicas", "degrade-env",
           "Restore policy: auto re-promotes when a repaired chip reports in;"
           " manual waits for an operator restore_full_degree() call."),
        # -------------------------------------------------- device plane
        _k("TORCHFT_XLA_HEARTBEAT_SEC", "float", "10", "api.md#process-groups", "tuning-env",
           "XLA process-group peer heartbeat timeout."),
        _k("TORCHFT_WATCHDOG_TIMEOUT_SEC", "float", "30", "api.md#futures", "tuning-env",
           "Future-watchdog deadline that converts a wedged wait into an error."),
        _k("TORCHFT_TPU_ATTENTION", "enum(auto|splash|flash|reference)", "auto",
           "api.md#models", None, "Attention kernel selector."),
        _k("TORCHFT_TPU_SPLASH_BLOCK", "int", "", "api.md#models", None,
           "Splash-attention tile override (both dimensions)."),
        _k("TORCHFT_TPU_SPLASH_BLOCK_KV", "int", "", "api.md#models", None,
           "Splash-attention kv-side tile override."),
        _k("TORCHFT_TPU_SCAN_UNROLL", "int", "1", "api.md#models", None,
           "Layer-scan unroll factor (benchmarking)."),
    ]
}


def is_registered(name: str) -> bool:
    return name in REGISTRY


def all_knobs() -> Dict[str, Knob]:
    """A copy of the registry (name -> Knob)."""
    return dict(REGISTRY)


# ---------------------------------------------------------------------------
# Override layer (policy plane). The adaptive policy engine retargets knobs
# at the Manager's quorum safe point by installing string values here;
# every read funnelled through env_raw sees an override before the process
# environment, so the central registry stays the single source of truth
# fleetlint's env-contract checks hang off — an override can only name a
# registered knob. Overrides are process-local and never mutate os.environ
# (a policy rollback must not leave residue in the environment).
_overrides: Dict[str, str] = {}
_overrides_mu = threading.Lock()


def set_override(name: str, value: Optional[str]) -> None:
    """Install (or, with ``None``, clear) one override. The name must be
    registered; values are strings exactly as an env var would carry."""
    if name not in REGISTRY:
        raise KeyError(
            f"{name} is not in the TORCHFT knob registry — overrides can "
            "only retarget registered knobs"
        )
    with _overrides_mu:
        if value is None:
            _overrides.pop(name, None)
        else:
            _overrides[name] = str(value)


def get_overrides() -> Dict[str, str]:
    """Snapshot of the active override set (name -> value)."""
    with _overrides_mu:
        return dict(_overrides)


def clear_overrides() -> None:
    """Drop every active override (the policy kill switch)."""
    with _overrides_mu:
        _overrides.clear()


@contextlib.contextmanager
def override_scope(values: Dict[str, str]) -> Iterator[None]:
    """Scoped knob overrides: install ``values`` on entry, restore the
    previous override state on exit. Nesting composes (inner scopes win
    while active). Unregistered names raise before anything is changed."""
    for name in values:
        if name not in REGISTRY:
            raise KeyError(
                f"{name} is not in the TORCHFT knob registry — overrides "
                "can only retarget registered knobs"
            )
    with _overrides_mu:
        saved = dict(_overrides)
        _overrides.update({k: str(v) for k, v in values.items()})
    try:
        yield
    finally:
        with _overrides_mu:
            _overrides.clear()
            _overrides.update(saved)


def env_raw(name: str, default: Optional[str] = None) -> Optional[str]:
    """``os.environ.get`` gated on registration: reading a knob that was
    never declared is a contract bug, surfaced here instead of shipping as
    an undocumented env var. Active policy overrides (``override_scope``)
    take precedence over the process environment."""
    if name not in REGISTRY:
        raise KeyError(
            f"{name} is not in the TORCHFT knob registry "
            "(torchft_tpu/knobs.py) — register it with a type, default, "
            "doc anchor, and doctor coverage before reading it"
        )
    with _overrides_mu:
        if name in _overrides:
            return _overrides[name]
    return os.environ.get(name, default)


def _typed(name: str, default: T, cast: Callable[[str], T]) -> T:
    raw = env_raw(name)
    if raw is None or raw == "":
        return default
    return cast(raw)


def env_str(name: str, default: str = "") -> str:
    return _typed(name, default, str)


def env_int(name: str, default: int = 0) -> int:
    return _typed(name, default, int)


def env_float(name: str, default: float = 0.0) -> float:
    return _typed(name, default, float)


def env_bool(name: str, default: bool = False) -> bool:
    raw = env_raw(name)
    if raw is None or raw.strip() == "":
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off")
