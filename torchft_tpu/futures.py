"""Timeout engine for futures and blocking contexts.

Design follows the reference's ``torchft/futures.py:27-354``: a singleton
manager owning a background asyncio event loop thread that arms timers for

- ``future_timeout(fut, timeout)`` — returns a future that raises
  ``TimeoutError`` if the inner one does not complete in time,
- ``future_wait(fut, timeout)`` — blocking wait with timeout,
- ``context_timeout(callback, timeout)`` — context manager invoking
  ``callback`` (typically ``pg.abort``) if the block does not exit in time,

plus a watchdog thread that hard-exits the process if the event loop itself
wedges (reference: torchft/futures.py:102-125, ``TORCHFT_WATCHDOG_TIMEOUT_SEC``).
There is no stream_timeout equivalent: JAX has no user streams; device-side
completion is observed via ``jax.Array.block_until_ready`` on a worker thread
instead (see ``process_group_xla``).
"""

from __future__ import annotations

import asyncio
import os
import sys
import threading
from contextlib import contextmanager
from datetime import timedelta
from typing import Callable, Generator, Optional, TypeVar

from torchft_tpu.work import Future

T = TypeVar("T")

WATCHDOG_TIMEOUT_SEC = float(os.environ.get("TORCHFT_WATCHDOG_TIMEOUT_SEC", 30.0))

__all__ = [
    "future_timeout",
    "future_wait",
    "context_timeout",
    "arm_deadline",
    "stop_timeout_manager",
]


def _to_seconds(timeout: "float | timedelta") -> float:
    if isinstance(timeout, timedelta):
        return timeout.total_seconds()
    return float(timeout)


def _arm_on_loop(
    loop: asyncio.AbstractEventLoop, delay: float, fn: Callable[[], None]
) -> Callable[[], None]:
    """Schedule ``fn`` to run after ``delay`` on ``loop``; return a
    thread-safe cancel function.

    Lock-free by construction: the ``call_later`` handle is only ever touched
    on the loop thread. The ``dead`` flag is the synchronous kill switch —
    ``_cancel`` flips it on the caller's thread (a GIL-atomic store), and the
    fire wrapper re-checks it at invocation time, so once ``_cancel`` returns
    a not-yet-started ``fn`` can no longer run even if the loop is backed up
    and processes the deadline before the revoke. The only residual race is
    ``fn`` already mid-execution at cancel time, which no timer design can
    close from outside.
    """
    slot: "list[Optional[asyncio.TimerHandle]]" = [None]
    dead = False

    def _fire() -> None:
        if not dead:
            fn()

    def _install() -> None:
        if not dead:
            slot[0] = loop.call_later(delay, _fire)

    loop.call_soon_threadsafe(_install)

    def _cancel() -> None:
        nonlocal dead
        dead = True

        def _revoke() -> None:
            if slot[0] is not None:
                slot[0].cancel()
                slot[0] = None

        try:
            loop.call_soon_threadsafe(_revoke)
        except RuntimeError:
            pass  # loop already shut down; nothing left to fire

    return _cancel


class _TimeoutManager:
    """Singleton owning the timer event loop + watchdog."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # Per-generation shutdown signal: a restart after shutdown() creates a
        # fresh Event, so a lingering watchdog from the previous generation
        # only ever observes its own.
        self._shutdown_evt: Optional[threading.Event] = None

    def _maybe_start(self) -> asyncio.AbstractEventLoop:
        with self._lock:
            if self._loop is None:
                loop = asyncio.new_event_loop()
                thread = threading.Thread(
                    target=loop.run_forever, daemon=True, name="torchft_timeout_loop"
                )
                thread.start()
                self._loop = loop
                shutdown_evt = threading.Event()
                self._shutdown_evt = shutdown_evt
                threading.Thread(
                    target=self._watchdog_loop,
                    args=(loop, shutdown_evt),
                    daemon=True,
                    name="torchft_watchdog",
                ).start()
            return self._loop

    def _watchdog_loop(
        self, loop: asyncio.AbstractEventLoop, shutdown_evt: threading.Event
    ) -> None:
        # Periodically schedule a no-op on the event loop; if it fails to run
        # within the watchdog budget the loop is wedged (a timer callback is
        # stuck, likely inside an abort) — kill the process rather than hang
        # training forever. Matches reference torchft/futures.py:102-125.
        ticked = threading.Event()
        while not shutdown_evt.is_set():
            ticked.clear()
            try:
                loop.call_soon_threadsafe(ticked.set)
            except RuntimeError:
                return  # loop closed
            if not ticked.wait(WATCHDOG_TIMEOUT_SEC):
                if shutdown_evt.is_set():
                    return
                print(
                    "torchft_tpu watchdog: timeout event loop is stuck for "
                    f"{WATCHDOG_TIMEOUT_SEC}s, exiting process",
                    file=sys.stderr,
                    flush=True,
                )
                os._exit(1)
            # Tick at half the watchdog budget; wakes immediately on shutdown.
            shutdown_evt.wait(WATCHDOG_TIMEOUT_SEC / 2)

    def shutdown(self) -> None:
        with self._lock:
            if self._shutdown_evt is not None:
                self._shutdown_evt.set()
                self._shutdown_evt = None
            if self._loop is not None:
                loop = self._loop
                self._loop = None
                loop.call_soon_threadsafe(loop.stop)

    # -- public ops -------------------------------------------------------
    def register(self, fut: Future[T], timeout: float) -> Future[T]:
        loop = self._maybe_start()
        out: Future[T] = Future()

        def _on_timeout() -> None:
            if not out.done():
                try:
                    out.set_exception(
                        TimeoutError(f"future did not complete within {timeout}s")
                    )
                except RuntimeError:
                    pass

        cancel_timer = _arm_on_loop(loop, timeout, _on_timeout)

        def _transfer(f: Future[T]) -> None:
            cancel_timer()
            if out.done():
                return
            try:
                exc = f.exception()
                if exc is not None:
                    out.set_exception(exc)
                else:
                    out.set_result(f.value())
            except RuntimeError:
                pass  # lost the race with the timeout

        fut.add_done_callback(_transfer)
        return out

    def arm(self, callback: Callable[[], None], timeout: float) -> Callable[[], None]:
        return _arm_on_loop(self._maybe_start(), timeout, callback)

    def context_timeout(
        self, callback: Callable[[], None], timeout: float
    ) -> "Generator[None, None, None]":
        @contextmanager
        def _ctx() -> Generator[None, None, None]:
            cancel = self.arm(callback, timeout)
            try:
                yield
            finally:
                cancel()

        return _ctx()


_TIMEOUT_MANAGER = _TimeoutManager()


def future_timeout(fut: Future[T], timeout: "float | timedelta") -> Future[T]:
    """Return a future failing with TimeoutError if ``fut`` is late."""
    return _TIMEOUT_MANAGER.register(fut, _to_seconds(timeout))


def future_wait(fut: Future[T], timeout: "float | timedelta") -> T:
    """Wait for ``fut`` up to ``timeout``; raises TimeoutError on expiry."""
    return fut.wait(timeout=_to_seconds(timeout))


def context_timeout(
    callback: Callable[[], None], timeout: "float | timedelta"
) -> "Generator[None, None, None]":
    """Context manager calling ``callback`` if the block overruns ``timeout``.

    Used to arm abort watchdogs around blocking collectives, mirroring the
    reference's abort-based timeout recovery (torchft/process_group.py:739-763).
    """
    return _TIMEOUT_MANAGER.context_timeout(callback, _to_seconds(timeout))


def arm_deadline(
    callback: Callable[[], None], timeout: "float | timedelta"
) -> Callable[[], None]:
    """Arm ``callback`` to fire after ``timeout``; returns a cancel function.

    The bare-timer primitive behind ``context_timeout``, for ops whose
    completion signal is a future resolving rather than a ``with`` block
    exiting — cancel from the future's done-callback so the deadline covers
    the full async span, not just the dispatching frame.
    """
    return _TIMEOUT_MANAGER.arm(callback, _to_seconds(timeout))


def stop_timeout_manager() -> None:
    """Shut down the background loop (test teardown only)."""
    _TIMEOUT_MANAGER.shutdown()
