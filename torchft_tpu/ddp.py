"""Fault-tolerant data parallelism helpers.

Role-equivalent of the reference's torchft/ddp.py:31-104. Torch DDP installs
autograd-hook comm buckets; JAX has explicit gradients, so the idiomatic
equivalent is a function (and an optax transform) that averages a gradient
pytree across replica groups through the Manager — picking up quorum
participation, zero-contribution for non-participants, and error swallowing.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

from torchft_tpu.manager import Manager
from torchft_tpu.process_group import ReduceOp
from torchft_tpu.work import GradStream, Work

__all__ = ["DistributedDataParallel", "PureDistributedDataParallel", "ft_allreduce_gradients"]


def ft_allreduce_gradients(
    manager: Manager, grads: Any, should_quantize: bool = False
) -> Any:
    """Average a gradient pytree across participating replica groups.

    Blocking convenience over the managed allreduce (reference comm-hook
    behavior, ddp.py:66-79): on communicator failure the step's gradients
    resolve to zeros and ``manager.should_commit()`` will discard the step.
    Routes through the streaming bucket pipeline (bit-identical to the
    serial path when uncompressed) so buckets unpack while later ones are
    still on the wire. ``should_quantize=True`` streams too where the
    Manager supports it (host PG, streaming on) — buckets ride the wire
    fp8/int8-compressed with error feedback — and otherwise falls back to
    the monolithic quantized collective inside the Manager.
    """
    return manager.allreduce_streamed(
        grads, should_quantize=should_quantize
    ).wait()


class DistributedDataParallel:
    """Bundles a Manager with gradient averaging for the replicated dim.

    The single-tree variant issues one allreduce for the whole gradient
    pytree (reference DDP buckets exist to batch hook-delivered grads; with
    explicit grads one tree-level collective is already "bucketed").
    """

    def __init__(self, manager: Manager, should_quantize: bool = False) -> None:
        self._manager = manager
        self._should_quantize = should_quantize

    def allreduce_gradients(self, grads: Any) -> Work:
        """Async: returns a Work whose future resolves to averaged grads."""
        return self._manager.allreduce(grads, should_quantize=self._should_quantize)

    def allreduce_gradients_streamed(self, grads: Any) -> GradStream:
        """Async with per-bucket completion: a GradStream whose ``ready(i)``
        flips as each bucket lands. Quantized trees stream compressed
        buckets where the Manager supports it (host PG, streaming on) and
        degenerate to one bucket otherwise (the monolithic fp8 pipeline
        packs its own wire buffer)."""
        return self._manager.allreduce_streamed(
            grads, should_quantize=self._should_quantize
        )

    def average_gradients(self, grads: Any) -> Any:
        """Blocking: returns the averaged gradient pytree."""
        return self.allreduce_gradients_streamed(grads).wait()


class PureDistributedDataParallel(DistributedDataParallel):
    """Per-bucket variant (reference's per-parameter hooks, ddp.py:82-104):
    leaves pack into flat same-dtype buckets (shared
    ``torchft_tpu/bucketing.py``) and one allreduce is issued per bucket, so
    later buckets overlap earlier ones while a pytree of hundreds of leaves
    still costs only ``ceil(total_bytes / cap)`` collectives. Quantized
    trees stream compressed buckets with error feedback when the Manager
    supports it (host PG, streaming on); otherwise the Manager falls back
    to its monolithic quantized collective."""

    def __init__(
        self,
        manager: Manager,
        should_quantize: bool = False,
        bucket_cap_bytes: Optional[int] = None,
    ) -> None:
        from torchft_tpu.bucketing import DEFAULT_BUCKET_CAP_BYTES

        super().__init__(manager, should_quantize)
        self._bucket_cap_bytes = (
            int(bucket_cap_bytes)
            if bucket_cap_bytes is not None
            else DEFAULT_BUCKET_CAP_BYTES
        )

    def average_gradients(self, grads: Any) -> Any:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(grads)
        if len(leaves) <= 1 or self._bucket_cap_bytes <= 0:
            works = [
                self._manager.allreduce(
                    leaf, should_quantize=self._should_quantize
                )
                for leaf in leaves
            ]
            reduced = [w.get_future().wait() for w in works]
            return jax.tree_util.tree_unflatten(treedef, reduced)

        # one streamed managed allreduce carrying THIS wrapper's cap: the
        # Manager packs/unpacks with the shared bucketing plan and streams
        # per-bucket collectives, so later buckets ride the wire while
        # earlier ones unpack — strictly more overlap than the old
        # pack-here-then-wait-per-flat shape, same numerics. Quantized
        # trees take the same call: the Manager streams them compressed
        # (host PG, streaming on) or falls back to its monolithic
        # quantized collective.
        return self._manager.allreduce_streamed(
            grads,
            bucket_cap_bytes=self._bucket_cap_bytes,
            should_quantize=self._should_quantize,
        ).wait()
