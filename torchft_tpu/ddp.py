"""Fault-tolerant data parallelism helpers.

Role-equivalent of the reference's torchft/ddp.py:31-104. Torch DDP installs
autograd-hook comm buckets; JAX has explicit gradients, so the idiomatic
equivalent is a function (and an optax transform) that averages a gradient
pytree across replica groups through the Manager — picking up quorum
participation, zero-contribution for non-participants, and error swallowing.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

from torchft_tpu.manager import Manager
from torchft_tpu.process_group import ReduceOp
from torchft_tpu.work import Work

__all__ = ["DistributedDataParallel", "PureDistributedDataParallel", "ft_allreduce_gradients"]


def ft_allreduce_gradients(
    manager: Manager, grads: Any, should_quantize: bool = False
) -> Any:
    """Average a gradient pytree across participating replica groups.

    Blocking convenience over ``manager.allreduce`` (reference comm-hook
    behavior, ddp.py:66-79): on communicator failure the step's gradients
    resolve to zeros and ``manager.should_commit()`` will discard the step.
    """
    return manager.allreduce(grads, should_quantize=should_quantize).get_future().wait()


class DistributedDataParallel:
    """Bundles a Manager with gradient averaging for the replicated dim.

    The single-tree variant issues one allreduce for the whole gradient
    pytree (reference DDP buckets exist to batch hook-delivered grads; with
    explicit grads one tree-level collective is already "bucketed").
    """

    def __init__(self, manager: Manager, should_quantize: bool = False) -> None:
        self._manager = manager
        self._should_quantize = should_quantize

    def allreduce_gradients(self, grads: Any) -> Work:
        """Async: returns a Work whose future resolves to averaged grads."""
        return self._manager.allreduce(grads, should_quantize=self._should_quantize)

    def average_gradients(self, grads: Any) -> Any:
        """Blocking: returns the averaged gradient pytree."""
        return self.allreduce_gradients(grads).get_future().wait()


class PureDistributedDataParallel(DistributedDataParallel):
    """Per-leaf variant: one allreduce per parameter leaf, which lets later
    leaves overlap with earlier ones (reference: ddp.py:82-104)."""

    def average_gradients(self, grads: Any) -> Any:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(grads)
        works = [
            self._manager.allreduce(leaf, should_quantize=self._should_quantize)
            for leaf in leaves
        ]
        reduced = [w.get_future().wait() for w in works]
        return jax.tree_util.tree_unflatten(treedef, reduced)
