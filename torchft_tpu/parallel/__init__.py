from torchft_tpu.parallel.mesh import (
    batch_sharding,
    llama_param_specs,
    make_hsdp_mesh,
    make_train_step,
    shard_params,
)
from torchft_tpu.parallel.ring_attention import make_ring_attention_fn, ring_attention
from torchft_tpu.parallel.ulysses import make_ulysses_attention_fn, ulysses_attention

__all__ = [
    "make_hsdp_mesh",
    "llama_param_specs",
    "batch_sharding",
    "shard_params",
    "make_train_step",
    "ring_attention",
    "make_ring_attention_fn",
    "ulysses_attention",
    "make_ulysses_attention_fn",
]
