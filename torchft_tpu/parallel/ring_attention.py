"""Ring attention: context/sequence parallelism for long sequences.

Not present in the reference (SURVEY.md §5: sequence scaling is delegated to
torchtitan) but first-class here: causal flash-style attention where the KV
shards rotate around the ``sp`` mesh axis via ``ppermute`` while each device
keeps its Q shard, with online-softmax accumulation — compute overlaps the
ICI transfer and per-device memory stays O(S/P).

Use ``make_ring_attention_fn(mesh)`` as the ``attention_fn`` of
``llama_forward``; it shard_maps over (dp, fsdp, sp, tp) and runs
``ring_attention`` per shard.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention", "make_ring_attention_fn", "make_sp_attention_fn"]


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
) -> jax.Array:
    """Causal ring attention over ``axis_name``.

    Call inside shard_map. q: [B, S_loc, Hq, hd]; k/v: [B, S_loc, Hkv, hd]
    (local sequence shards; global position = axis_index * S_loc + offset).
    Returns [B, S_loc, Hq, hd] in q's dtype.
    """
    P_ = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, S, Hq, hd = q.shape
    Hkv = k.shape[2]
    groups = Hq // Hkv

    q32 = q.astype(jnp.float32)
    q_pos = my_idx * S + jnp.arange(S)  # [S]
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))

    # online softmax accumulators
    m0 = jnp.full((B, Hq, S), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, Hq, S), jnp.float32)
    o0 = jnp.zeros((B, S, Hq, hd), jnp.float32)

    def body(i, carry):
        m, l, o, k_blk, v_blk = carry
        kv_idx = (my_idx - i) % P_
        kv_pos = kv_idx * S + jnp.arange(S)  # [S]

        k_rep = jnp.repeat(k_blk, groups, axis=2).astype(jnp.float32)
        v_rep = jnp.repeat(v_blk, groups, axis=2).astype(jnp.float32)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q32, k_rep) * scale
        causal = q_pos[:, None] >= kv_pos[None, :]  # [Sq, Sk]
        scores = jnp.where(causal[None, None], scores, -jnp.inf)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        # fully-masked rows keep m_new == -inf; use a zero surrogate so the
        # exps below stay finite (their probabilities are zeroed by `causal`)
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.where(
            jnp.isneginf(scores), 0.0, jnp.exp(scores - m_safe[..., None])
        )  # [B,H,Sq,Sk]
        alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))  # [B,H,Sq]
        l = alpha * l + jnp.sum(p, axis=-1)
        o = alpha.transpose(0, 2, 1)[..., None] * o + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v_rep
        )
        m = m_new

        # rotate the KV shard to the next device on the ring
        perm = [(j, (j + 1) % P_) for j in range(P_)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return m, l, o, k_blk, v_blk

    m, l, o, _, _ = jax.lax.fori_loop(0, P_, body, (m0, l0, o0, k, v))
    l_t = l.transpose(0, 2, 1)[..., None]  # [B,S,H,1]
    out = jnp.where(l_t > 0, o / jnp.maximum(l_t, 1e-20), 0.0)
    return out.astype(q.dtype)


def make_sp_attention_fn(mesh: Mesh, kernel):
    """Shared shard_map wrapper for the sequence-parallel attention
    strategies: ``kernel(q, k, v, cfg)`` runs per shard under the one
    (dp, fsdp) x sp x tp sharding contract, so ring and ulysses cannot
    drift apart on specs."""
    from torchft_tpu.utils import import_shard_map
    shard_map = import_shard_map()

    qspec = P(("dp", "fsdp"), "sp", "tp", None)

    def attention_fn(q, k, v, cfg):
        fn = shard_map(
            partial(kernel, cfg=cfg),
            mesh=mesh,
            in_specs=(qspec, qspec, qspec),
            out_specs=qspec,
            check_vma=False,
        )
        return fn(q, k, v)

    return attention_fn


def make_ring_attention_fn(mesh: Mesh):
    """Attention fn for llama_forward: shard_map of ring_attention.

    Sharding: batch over (dp, fsdp), sequence over sp, heads over tp
    (tp must divide n_kv_heads).
    """
    def kernel(q, k, v, cfg):
        return ring_attention(q, k, v, axis_name="sp")

    return make_sp_attention_fn(mesh, kernel)
