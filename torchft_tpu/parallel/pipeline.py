"""Pipeline parallelism: GPipe over a ``pp`` mesh axis.

The reference's only pipeline use is torch.distributed.pipelining model
splitting to create DiLoCo fragments (SURVEY.md §2.4, train_diloco.py); a
TPU-native framework owns the real thing. Design:

- **Layers are already scanned** over a stacked leading dim (models/llama),
  so a pipeline stage is just that stack sharded over ``pp``: each device
  holds ``L/P`` layers and runs its local sub-scan.
- **Microbatch rotation via ppermute.** A static tick loop (``M + P - 1``
  ticks for M microbatches over P stages) where every tick runs the local
  stage and rotates activations one stage down the ring. Bubble ticks
  compute-and-discard (`jnp.where` selects), keeping control flow
  compiler-static — no data-dependent branching, exactly one compiled tick
  body.
- **SPMD composition.** Everything runs inside ``shard_map``; the tick
  count ``M + P - 1`` is static (mesh axis size), so the loop lowers to a
  scan and is reverse-differentiable — pipeline backward falls out of
  jax.grad with no hand-written schedule.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "pipeline_apply",
    "make_pp_llama_loss",
    "pp_param_specs",
    "pp_degrade_axes",
]


def pipeline_apply(
    layer_fn: Callable[[Any, Any], Any],
    layer_params: Any,
    x: jax.Array,
    axis_name: str = "pp",
    num_microbatches: Optional[int] = None,
) -> jax.Array:
    """Run stacked layers as a pipeline over ``axis_name``. Call inside
    shard_map.

    ``layer_fn(h, one_layer_params) -> (h, None)`` is the scanned layer body;
    ``layer_params`` leaves are the LOCAL stage's stack [L/P, ...];
    ``x`` [B, ...] is this device's full activation batch. Returns the
    pipeline output on the LAST stage; zeros elsewhere (callers psum-select).
    """
    P_ = lax.psum(1, axis_name)  # static: mesh axis size
    stage = lax.axis_index(axis_name)
    M = num_microbatches or P_
    B = x.shape[0]
    assert B % M == 0, f"batch {B} must divide into {M} microbatches"
    mubs = x.reshape(M, B // M, *x.shape[1:])

    def local_stack(h):
        h, _ = lax.scan(layer_fn, h, layer_params)
        return h

    perm = [(i, (i + 1) % P_) for i in range(P_)]
    state = jnp.zeros_like(mubs[0])
    out = jnp.zeros_like(mubs)

    def tick(t, carry):
        state, out = carry
        # stage 0 ingests microbatch t; other stages take the rotated state
        inject = lax.dynamic_index_in_dim(
            mubs, jnp.clip(t, 0, M - 1), 0, keepdims=False
        )
        h_in = jnp.where(stage == 0, inject, state)
        h_out = local_stack(h_in)
        # the last stage emits microbatch t-(P-1) once the pipe is full
        emit_idx = t - (P_ - 1)
        emitted = lax.dynamic_update_index_in_dim(
            out, h_out, jnp.clip(emit_idx, 0, M - 1), 0
        )
        out = jnp.where((stage == P_ - 1) & (emit_idx >= 0), emitted, out)
        state = lax.ppermute(h_out, axis_name, perm)
        return state, out

    state, out = lax.fori_loop(0, M + P_ - 1, tick, (state, out), unroll=False)
    return out.reshape(B, *x.shape[1:])


def pp_param_specs(cfg: Any) -> Any:
    """PartitionSpecs for the llama pytree with layers sharded over pp.

    Within-layer dims could additionally carry fsdp/tp exactly as in
    llama_param_specs; kept pp-pure here so the pipeline axis composes by
    spec merge when needed.
    """
    return {
        "embed": P(None, None),
        "layers": {
            "attn_norm": P("pp", None),
            "wq": P("pp", None, None),
            "wk": P("pp", None, None),
            "wv": P("pp", None, None),
            "wo": P("pp", None, None),
            "ffn_norm": P("pp", None),
            "w_gate": P("pp", None, None),
            "w_up": P("pp", None, None),
            "w_down": P("pp", None, None),
        },
        "final_norm": P(None),
        "lm_head": P(None, None),
    }


def pp_degrade_axes(cfg: Any) -> Any:
    """Degrade-in-place hook: per-leaf reshard axes for shrinking the
    pipeline by one stage. Layer stacks are sharded over ``pp`` on dim 0,
    so losing a stage is a dim-0 reshard of every ``layers`` leaf: each of
    the P-1 survivors picks up a slightly deeper local sub-stack
    (np.array_split semantics), and the scanned sub-stacks still
    concatenate to the identical full model — the bubble count just grows
    by the shrunken P. Feed this to degrade.reshard_from_survivors /
    reshard_full."""
    from torchft_tpu.parallel.degrade import axes_from_specs

    return axes_from_specs(pp_param_specs(cfg), "pp")


def make_pp_llama_loss(cfg: Any, mesh: Mesh, num_microbatches: Optional[int] = None,
                       remat: Any = "dots"):
    """Build a pipeline-parallel llama loss fn over mesh axis ``pp``.

    Embedding and the LM head run replicated on every stage (they are cheap
    relative to the layer stack at depth); only the last stage's logits are
    real, selected by a psum mask. Returns loss_fn(params, tokens, targets).

    The layer body is the canonical one (models/llama.make_llama_layer_body)
    wrapped in the shared remat policy — at the 8B/70B depths pipelining
    targets, per-stage activation residency without remat would hit the HBM
    ceiling.
    """
    from torchft_tpu.utils import import_shard_map
    shard_map = import_shard_map()

    from torchft_tpu.models.llama import _rmsnorm, make_llama_layer_body
    from torchft_tpu.models.remat import remat_wrap

    layer = remat_wrap(make_llama_layer_body(cfg), remat)

    def loss_local(layers, embed, final_norm, lm_head, tokens, targets):
        h = embed[tokens]
        h = pipeline_apply(
            layer, layers, h, axis_name="pp", num_microbatches=num_microbatches
        )
        # only the last stage holds real activations: mask-and-psum selects
        # them onto every stage (logit-sized allreduce; fine at loss time)
        P_ = lax.psum(1, "pp")
        is_last = (lax.axis_index("pp") == P_ - 1).astype(h.dtype)
        h = lax.psum(h * is_last, "pp")
        h = _rmsnorm(h, final_norm, cfg.norm_eps)
        logits = (h @ lm_head).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        return jnp.mean(lse - tgt)

    layer_specs = pp_param_specs(cfg)["layers"]

    def loss_fn(params, tokens, targets):
        fn = shard_map(
            loss_local,
            mesh=mesh,
            in_specs=(layer_specs, P(None, None), P(None), P(None, None), P(None, None), P(None, None)),
            out_specs=P(),
            check_vma=False,
        )
        return fn(
            params["layers"],
            params["embed"],
            params["final_norm"],
            params["lm_head"],
            tokens,
            targets,
        )

    return loss_fn
