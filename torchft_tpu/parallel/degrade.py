"""Degrade-in-place reshard engine: remap a sharded param tree from a
k-chip mesh onto a (k-1)-chip mesh, bitwise.

The repo's fault model (PAPER.md) was *across* replica groups only: one
dead chip cost its whole group — leave the quorum, heal, rejoin. This
module is the data-plane half of the degrade plane
(docs/operations.md#degraded-replicas): when a group member dies the
survivors reshard the param tree onto themselves and the group stays in
the quorum as a slower member.

Two reshard paths, both bitwise-equal to the pre-fault params:

- :func:`reshard_from_survivors` — **gather-free**: survivors keep their
  shards, only the dead rank's shard is sourced from outside the group
  (the erasure/heal transport of the redundancy plane — peer-staged
  shards, ``checkpointing/transport.py``) via the ``shard_source``
  callback, then the k shards are re-split onto k-1 chips. Replicated
  leaves never move at all.
- :func:`reshard_full` — **full intra-group redistribution**: when no
  peer can source the lost shard, rebuild every leaf's (k-1)-way split
  from the host-side full copy (the Manager's user ``state_dict()``,
  which survives chip loss by construction).

Splitting uses ``np.array_split`` semantics (the first ``n % d`` shards
take one extra row), so reassembly is plain concatenation and
``concatenate(split(x)) == x`` holds bitwise for any degree — the
invariant :func:`assemble` verifies and tests/doctor pin.

The engine is numpy-level on purpose: it runs identically on the host
plane (doctor probes, CPU tests) and under a real mesh, where the caller
device_puts the returned per-chip trees onto the shrunken mesh
(:func:`torchft_tpu.parallel.mesh.shrink_mesh`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "DegradeConfig",
    "DegradeError",
    "DegradeStats",
    "axes_from_specs",
    "split_even",
    "assemble",
    "reshard_full",
    "reshard_from_survivors",
]

_RESTORE_POLICIES = ("auto", "manual")


class DegradeError(RuntimeError):
    """A reshard could not be completed (missing shard, shape mismatch)."""


@dataclass(frozen=True)
class DegradeConfig:
    """Degrade-plane policy knobs (``TORCHFT_DEGRADE_*``).

    ``enabled`` gates the whole plane: off (the default) leaves every
    Manager/PG code path byte-identical to pre-degrade behavior (pinned
    by tests). ``min_degree`` is the smallest surviving group degree
    worth resharding onto — below it a chip loss falls back to the
    classic leave-heal-rejoin path. ``restore`` picks who re-promotes a
    degraded group: ``auto`` (a repaired chip reporting in restores full
    degree) or ``manual`` (an operator restore_full_degree() call).
    """

    enabled: bool = False
    min_degree: int = 1
    restore: str = "auto"

    @staticmethod
    def from_env() -> "DegradeConfig":
        """Build from ``TORCHFT_DEGRADE_*``; raises ValueError on junk."""
        from torchft_tpu import knobs

        raw = knobs.env_raw("TORCHFT_DEGRADE")
        mode = (raw or "off").strip().lower() or "off"
        if mode not in ("off", "on"):
            raise ValueError(
                f"TORCHFT_DEGRADE={raw!r}: must be 'off' or 'on'"
            )
        raw_min = knobs.env_raw("TORCHFT_DEGRADE_MIN_DEGREE")
        try:
            min_degree = int(raw_min) if raw_min not in (None, "") else 1
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"TORCHFT_DEGRADE_MIN_DEGREE={raw_min!r}: {e}"
            ) from e
        raw_restore = knobs.env_raw("TORCHFT_DEGRADE_RESTORE")
        restore = (raw_restore or "auto").strip().lower() or "auto"
        cfg = DegradeConfig(
            enabled=(mode == "on"), min_degree=min_degree, restore=restore
        )
        cfg.validate()
        return cfg

    def validate(self) -> None:
        if self.min_degree < 1:
            raise ValueError(
                f"min_degree must be >= 1, got {self.min_degree}"
            )
        if self.restore not in _RESTORE_POLICIES:
            raise ValueError(
                f"TORCHFT_DEGRADE_RESTORE={self.restore!r}: must be one of"
                f" {_RESTORE_POLICIES}"
            )


@dataclass
class DegradeStats:
    """What a reshard cost; surfaced via Manager timings/breadcrumbs."""

    mode: str = ""  # "peer" (gather-free) | "full" (redistribution)
    leaves_total: int = 0
    leaves_sharded: int = 0
    leaves_replicated: int = 0
    bytes_sourced: int = 0  # fetched from outside the group (dead shard)
    bytes_moved: int = 0  # re-split bytes placed onto survivors

    def to_json(self) -> Dict[str, Any]:
        return {
            "mode": self.mode,
            "leaves_total": self.leaves_total,
            "leaves_sharded": self.leaves_sharded,
            "leaves_replicated": self.leaves_replicated,
            "bytes_sourced": self.bytes_sourced,
            "bytes_moved": self.bytes_moved,
        }


def _tree_parts(tree: Any, none_is_leaf: bool = False):
    import jax

    # An axes tree carries None for replicated leaves; None is normally an
    # EMPTY pytree node and would silently drop out of the flatten,
    # misaligning axes against params — flag it as a leaf there.
    kwargs = {"is_leaf": (lambda x: x is None)} if none_is_leaf else {}
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(
        tree, **kwargs
    )
    paths = [jax.tree_util.keystr(p) for p, _ in leaves_with_paths]
    leaves = [leaf for _, leaf in leaves_with_paths]
    return paths, leaves, treedef


def axes_from_specs(specs: Any, axis_name: str) -> Any:
    """Map a PartitionSpec tree to per-leaf reshard axes for ``axis_name``.

    Each leaf becomes the tensor dim index whose spec entry mentions
    ``axis_name`` (entries may be a name or a tuple of names), or None if
    the leaf is replicated over that axis. This is how mesh.py's TP specs
    and pipeline.py's pp specs project onto the degrade engine.
    """
    import jax

    def _axis(spec: Any) -> Optional[int]:
        if spec is None:
            return None
        for dim, entry in enumerate(tuple(spec)):
            if entry is None:
                continue
            names = entry if isinstance(entry, tuple) else (entry,)
            if axis_name in names:
                return dim
        return None

    return jax.tree_util.tree_map(
        _axis,
        specs,
        is_leaf=lambda x: x is None or not isinstance(x, dict),
    )


def split_even(arr: np.ndarray, degree: int, axis: int) -> List[np.ndarray]:
    """Split ``arr`` into ``degree`` contiguous chunks along ``axis``
    (np.array_split semantics: the first ``n % degree`` chunks get one
    extra row). Concatenating the result reproduces ``arr`` bitwise."""
    if degree < 1:
        raise DegradeError(f"split degree must be >= 1, got {degree}")
    a = np.asarray(arr)
    if a.ndim <= axis:
        raise DegradeError(
            f"cannot split a rank-{a.ndim} array along axis {axis}"
        )
    return [np.ascontiguousarray(s) for s in np.array_split(a, degree, axis)]


def assemble(shard_trees: Sequence[Any], axes: Any) -> Any:
    """Inverse of a reshard: concatenate per-chip trees back into the full
    tree (replicated leaves take chip 0's copy). Used by tests and the
    doctor probe to assert bitwise equality across a degrade."""
    import jax

    if not shard_trees:
        raise DegradeError("assemble needs at least one shard tree")

    def _join(axis: Optional[int], *leaves: Any) -> np.ndarray:
        arrs = [np.asarray(x) for x in leaves]
        if axis is None:
            return arrs[0]
        return np.concatenate(arrs, axis=axis)

    paths, axis_leaves, treedef = _tree_parts(axes, none_is_leaf=True)
    per_tree_leaves = [_tree_parts(t)[1] for t in shard_trees]
    out = [
        _join(axis_leaves[i], *[tl[i] for tl in per_tree_leaves])
        for i in range(len(axis_leaves))
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def reshard_full(
    full_tree: Any, axes: Any, new_degree: int
) -> "tuple[List[Any], DegradeStats]":
    """Full intra-group redistribution: split the host-side full params
    onto ``new_degree`` chips. Returns (per-chip trees, stats)."""
    import jax

    stats = DegradeStats(mode="full")
    paths, leaves, treedef = _tree_parts(full_tree)
    _, axis_leaves, _ = _tree_parts(axes, none_is_leaf=True)
    if len(leaves) != len(axis_leaves):
        raise DegradeError(
            f"axes tree has {len(axis_leaves)} leaves, params have "
            f"{len(leaves)}"
        )
    per_chip: List[List[np.ndarray]] = [[] for _ in range(new_degree)]
    for leaf, axis in zip(leaves, axis_leaves):
        a = np.asarray(leaf)
        stats.leaves_total += 1
        if axis is None:
            stats.leaves_replicated += 1
            for c in range(new_degree):
                per_chip[c].append(a)
            continue
        stats.leaves_sharded += 1
        shards = split_even(a, new_degree, axis)
        stats.bytes_moved += a.nbytes
        for c in range(new_degree):
            per_chip[c].append(shards[c])
    trees = [
        jax.tree_util.tree_unflatten(treedef, chip) for chip in per_chip
    ]
    return trees, stats


def reshard_from_survivors(
    rank_trees: Sequence[Any],
    dead_rank: int,
    axes: Any,
    shard_source: Optional[Callable[[str], np.ndarray]] = None,
) -> "tuple[List[Any], DegradeStats]":
    """Gather-free reshard: survivors contribute their shards in place;
    the dead rank's shard of each sharded leaf is sourced from a peer via
    ``shard_source(leaf_path) -> np.ndarray`` (the erasure/heal transport
    of the redundancy plane). Replicated leaves come straight from any
    survivor and never move.

    ``rank_trees[dead_rank]`` is ignored (typically None — the chip is
    gone). Returns (per-chip trees for the k-1 survivors, stats). Raises
    :class:`DegradeError` if a sharded leaf's lost shard cannot be
    sourced — callers fall back to :func:`reshard_full`.
    """
    import jax

    k = len(rank_trees)
    if not (0 <= dead_rank < k):
        raise DegradeError(f"dead_rank {dead_rank} out of range for k={k}")
    if k < 2:
        raise DegradeError("cannot shrink a 1-chip group")
    stats = DegradeStats(mode="peer")
    survivors = [r for r in range(k) if r != dead_rank]
    parts = [
        _tree_parts(rank_trees[r]) for r in survivors
    ]  # (paths, leaves, treedef) per survivor
    paths, _, treedef = parts[0]
    _, axis_leaves, _ = _tree_parts(axes, none_is_leaf=True)
    if len(axis_leaves) != len(paths):
        raise DegradeError(
            f"axes tree has {len(axis_leaves)} leaves, params have "
            f"{len(paths)}"
        )
    new_degree = k - 1
    per_chip: List[List[np.ndarray]] = [[] for _ in range(new_degree)]
    for i, (path, axis) in enumerate(zip(paths, axis_leaves)):
        stats.leaves_total += 1
        if axis is None:
            stats.leaves_replicated += 1
            a = np.asarray(parts[0][1][i])
            for c in range(new_degree):
                per_chip[c].append(a)
            continue
        stats.leaves_sharded += 1
        if shard_source is None:
            raise DegradeError(
                f"leaf {path} is sharded and rank {dead_rank}'s shard is "
                "lost: no shard_source to fetch it from a peer"
            )
        lost = np.asarray(shard_source(path))
        stats.bytes_sourced += lost.nbytes
        # reassemble in rank order, then re-split onto the survivors
        by_rank: List[np.ndarray] = []
        s_iter = iter(range(len(survivors)))
        for r in range(k):
            if r == dead_rank:
                by_rank.append(lost)
            else:
                by_rank.append(np.asarray(parts[next(s_iter)][1][i]))
        full = np.concatenate(by_rank, axis=axis)
        shards = split_even(full, new_degree, axis)
        stats.bytes_moved += full.nbytes
        for c in range(new_degree):
            per_chip[c].append(shards[c])
    trees = [
        jax.tree_util.tree_unflatten(treedef, chip) for chip in per_chip
    ]
    return trees, stats
