"""Device mesh + sharding rules: the intra-replica-group parallelism plane.

The reference delegates FSDP/TP/PP inside a replica group to
torchtitan/PyTorch composables and owns only the replicated dim
(reference README.md:40, fsdp_test.py:57-72). On TPU the equivalent is XLA
SPMD: pick a Mesh, annotate shardings, let XLA insert the collectives over
ICI. This module provides the mesh and the HSDP sharding rules for the
in-tree Llama family:

- axes: ``dp`` (fault-tolerant replicated dim — maps across replica groups /
  DCN), ``fsdp`` (ZeRO-style parameter sharding), ``tp`` (Megatron-style
  tensor parallel), ``sp`` (sequence/context parallel for ring attention)
- params: column-then-row tp sharding of attention/FFN matmuls, fsdp on the
  other dim; XLA inserts the all-gathers/reduce-scatters
- batch: sharded over (dp, fsdp); sequence over sp

The FT allreduce of torchft_tpu.manager applies across replica *groups* on
the host plane; within a single-controller multi-chip job the ``dp`` axis of
this mesh plays that role in-graph.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchft_tpu.models.llama import LlamaConfig

__all__ = [
    "make_hsdp_mesh",
    "shrink_mesh",
    "llama_param_specs",
    "degrade_axes",
    "shard_params",
    "batch_sharding",
    "make_train_step",
]


def make_hsdp_mesh(
    devices=None, dp: int = 1, fsdp: int = 1, tp: int = 1, sp: int = 1, ep: int = 1
) -> Mesh:
    """Build a 5-axis mesh. Axis order is outermost-first: dp rides the
    slowest links (DCN between replica groups), sp/tp the fastest (ICI).
    ``ep`` shards MoE experts (torchft_tpu/models/moe.py); dense-model specs
    simply never mention it."""
    devices = devices if devices is not None else jax.devices()
    n = dp * fsdp * ep * sp * tp
    assert len(devices) >= n, f"need {n} devices, have {len(devices)}"
    arr = np.asarray(devices[:n]).reshape(dp, fsdp, ep, sp, tp)
    return Mesh(arr, ("dp", "fsdp", "ep", "sp", "tp"))


def shrink_mesh(mesh: Mesh, axis_name: str, dead_index: int) -> Mesh:
    """Degrade-in-place hook: the same mesh minus one slice of ``axis_name``
    (the slice holding the dead chip). Axis order and the other axis sizes
    are preserved, so existing PartitionSpecs stay valid — only the named
    axis's degree drops by one. Param movement onto the shrunken mesh is
    the reshard engine's job (torchft_tpu/parallel/degrade.py)."""
    names = mesh.axis_names
    if axis_name not in names:
        raise ValueError(f"mesh has no axis {axis_name!r} (axes: {names})")
    axis = names.index(axis_name)
    devs = np.asarray(mesh.devices)
    if devs.shape[axis] < 2:
        raise ValueError(
            f"axis {axis_name!r} has degree {devs.shape[axis]}; nothing to"
            " shrink onto"
        )
    if not 0 <= dead_index < devs.shape[axis]:
        raise ValueError(
            f"dead_index {dead_index} out of range for axis {axis_name!r}"
            f" of degree {devs.shape[axis]}"
        )
    return Mesh(np.delete(devs, dead_index, axis=axis), names)


def llama_param_specs(cfg: LlamaConfig) -> Dict[str, Any]:
    """PartitionSpecs matching the llama_init pytree (HSDP + TP).

    Column-parallel projections (wq/wk/wv/w_gate/w_up) shard their output dim
    over tp; row-parallel (wo/w_down) shard their input dim over tp — XLA
    turns the seam into one psum per block, the Megatron pattern. The
    remaining big dim shards over fsdp (ZeRO-3).
    """
    return {
        "embed": P("fsdp", "tp"),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, "fsdp", "tp"),
            "wk": P(None, "fsdp", "tp"),
            "wv": P(None, "fsdp", "tp"),
            "wo": P(None, "tp", "fsdp"),
            "ffn_norm": P(None, None),
            "w_gate": P(None, "fsdp", "tp"),
            "w_up": P(None, "fsdp", "tp"),
            "w_down": P(None, "tp", "fsdp"),
        },
        "final_norm": P(None),
        "lm_head": P("fsdp", "tp"),
    }


def degrade_axes(cfg: LlamaConfig, axis_name: str = "tp") -> Dict[str, Any]:
    """Per-leaf reshard axes for shrinking ``axis_name`` in place: the
    llama HSDP specs projected through the degrade engine
    (torchft_tpu/parallel/degrade.py axes_from_specs)."""
    from torchft_tpu.parallel.degrade import axes_from_specs

    return axes_from_specs(llama_param_specs(cfg), axis_name)


def shard_params(params: Any, mesh: Mesh, specs: Any) -> Any:
    """device_put every leaf onto its NamedSharding."""
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)), params, specs
    )


def batch_sharding(mesh: Mesh, with_sp: bool = True) -> NamedSharding:
    """Tokens [B, S]: batch over (dp, fsdp), sequence over sp."""
    return NamedSharding(mesh, P(("dp", "fsdp"), "sp" if with_sp else None))


def make_train_step(
    cfg: LlamaConfig,
    tx: Any,  # optax.GradientTransformation
    mesh: Mesh,
    attention_fn: Optional[Callable] = None,
    donate: bool = True,
    remat: Any = "full",
) -> Callable:
    """Build the jitted HSDP train step.

    Gradients are implicitly summed across dp/fsdp by XLA (the loss mean over
    the batch spans those axes); params/opt state stay in their HSDP
    sharding. Returns fn(params, opt_state, tokens, targets) ->
    (params, opt_state, loss).
    """
    import optax

    from torchft_tpu.models.llama import llama_loss

    specs = llama_param_specs(cfg)
    param_shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), specs
    )
    tok_sharding = batch_sharding(mesh)

    def step(params, opt_state, tokens, targets):
        # Default remat="full": the sharded targets (8B/70B, long seq) sit at
        # the HBM edge; callers with headroom can pass "dots" (models/remat).
        loss, grads = jax.value_and_grad(llama_loss)(
            params, tokens, targets, cfg, attention_fn=attention_fn, remat=remat
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(
        step,
        in_shardings=(param_shardings, None, tok_sharding, tok_sharding),
        out_shardings=(param_shardings, None, None),
        donate_argnums=(0, 1) if donate else (),
    )
