"""Ulysses-style all-to-all sequence parallelism.

The second of the two long-context strategies (ring attention in
ring_attention.py is the first; the reference has neither — SURVEY.md §5
delegates sequence scaling to torchtitan). Instead of rotating KV shards
around the ``sp`` ring, one ``all_to_all`` re-partitions the sharding
axis: every device trades its sequence shard of ALL heads for the FULL
sequence of a head subset, runs ordinary causal attention locally (the
ops.attention dispatcher — splash/flash on TPU), and a second
``all_to_all`` restores sequence sharding.

Trade-offs vs ring attention:

- two all-to-alls per layer instead of P-1 ppermute hops — fewer, larger
  ICI transfers, and the local attention is a single dense-tiled kernel
  call (MXU-friendly) rather than P accumulation steps;
- with a tiled kernel (splash/flash on TPU) per-device attention
  memory matches ring's O(S * S/P); on the XLA fallback path the local
  attention materializes full [B, H/sp, S, S] scores — O(S^2) — so
  long-context off-TPU runs belong on ring attention;
- heads must divide: ``sp`` must divide the per-device head counts
  (after tp). GQA models with few KV heads hit this first — ring
  attention has no such constraint, which is why it stays the default.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
from jax.sharding import Mesh

__all__ = ["ulysses_attention", "make_ulysses_attention_fn"]


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    cfg: Any,
    axis_name: str = "sp",
) -> jax.Array:
    """Call inside shard_map. q: [B, S_loc, Hq, hd]; k/v: [B, S_loc,
    Hkv, hd] (sequence shards in mesh-axis order). Returns [B, S_loc,
    Hq, hd]."""
    from torchft_tpu.ops.attention import causal_attention

    sp = jax.lax.psum(1, axis_name)
    if sp == 1:
        return causal_attention(q, k, v, cfg)
    hq, hkv = q.shape[2], k.shape[2]
    if hq % sp or hkv % sp:
        raise ValueError(
            f"ulysses needs sp={sp} to divide the per-device head counts "
            f"(q heads {hq}, kv heads {hkv}); use ring attention for this "
            "config"
        )

    # head-scatter / sequence-gather: [B, S_loc, H, hd] -> [B, S, H/sp, hd]
    # (tiled all_to_all concatenates shards in axis order, so the gathered
    # sequence is in global order)
    a2a = partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=2,
        concat_axis=1, tiled=True,
    )
    out = causal_attention(a2a(q), a2a(k), a2a(v), cfg)
    # inverse: sequence-scatter / head-gather
    return jax.lax.all_to_all(
        out, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def make_ulysses_attention_fn(mesh: Mesh):
    """Attention fn for llama_forward: shard_map of ulysses_attention.

    Same sharding contract as make_ring_attention_fn (one shared wrapper,
    make_sp_attention_fn): batch over (dp, fsdp), sequence over sp, heads
    over tp — and additionally sp must divide the PER-DEVICE head counts
    (n_heads/tp, n_kv_heads/tp).
    """
    from torchft_tpu.parallel.ring_attention import make_sp_attention_fn

    def kernel(q, k, v, cfg):
        return ulysses_attention(q, k, v, cfg, axis_name="sp")

    return make_sp_attention_fn(mesh, kernel)
