"""Serving plane: health-gated inference workers pulling versioned,
compressed parameter snapshots from the training fleet.

Topology (ROADMAP item 4, docs/serving.md):

    trainers ──(commit path)──> SnapshotPublisher ──announce──> SnapshotRegistry
                                    │    │                            │
                              full pulls │ per-step deltas       health poll
                         (HTTPTransport) │ (fp8/int8 wire)      (lighthouse)
                                    ▼    ▼                            │
                                  ServeWorker <──── /serve/sources ───┘
                                    │
                                  /infer traffic

Every live replica publishes a **versioned parameter snapshot** stamped
``(quorum_id, step)`` on the commit path.  Full snapshots are staged
through the existing resumable checkpoint transport (ranged, crc32,
multi-source failover — no new serialization plane); per-step **deltas**
ride the PR 6 fp8/int8 codec with the same error-feedback discipline.

Bitwise invariant: the publisher keeps an error-feedback *reference*
``R`` and replays its own encoded delta on publish::

    delta_v = encode(params_v - R_{v-1});  R_v = R_{v-1} + decode(delta_v)

Full pulls serve ``R_v`` verbatim, so a worker that applies the delta
chain and a worker that full-pulls land on **bitwise-identical** flats,
in every compress mode.  The residual ``params - R`` stays bounded
because each delta re-encodes the full drift (telescoping), exactly the
allreduce error-feedback discipline.  A publisher that missed versions
(healed, restarted) bootstraps ``R`` with a worker-style full pull from
the registry's sources before publishing again, so all sources stay
byte-interchangeable mid-delta-walk.

Routing is health-gated: the registry polls the lighthouse ``/health``
summary and **drains** a replica from the serving set at ``warn`` —
strictly before healthwatch's warn→eject escalation removes it from
training.  Workers answer ``/infer`` from their last-applied snapshot
under a local lock, so a quorum reconfiguration (or a mid-pull source
death) never fails a request.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .observability import MetricsRegistry
from .ops.quantization import (
    COMPRESS_MODES,
    compress_bucket,
    decompress_bucket,
)
from .retry import RetryPolicy, retry_call

logger = logging.getLogger(__name__)

# --------------------------------------------------------------------------
# Env contract (docs/serving.md)
# --------------------------------------------------------------------------
SERVE_REGISTRY_ENV = "TORCHFT_SERVE_REGISTRY"
SERVE_MAX_LAG_ENV = "TORCHFT_SERVE_MAX_LAG"
SERVE_COMPRESS_ENV = "TORCHFT_SERVE_COMPRESS"
SERVE_POLL_S_ENV = "TORCHFT_SERVE_POLL_S"
SERVE_DRAIN_ON_ENV = "TORCHFT_SERVE_DRAIN_ON"
SERVE_PORT_ENV = "TORCHFT_SERVE_PORT"
SERVE_TIMEOUT_S_ENV = "TORCHFT_SERVE_TIMEOUT_S"

_DRAIN_POLICIES = ("warn", "eject")

Version = Tuple[int, int]  # (quorum_id, step) — lexicographic order


@dataclass
class ServeConfig:
    """Knobs for the serving plane (all overridable via TORCHFT_SERVE_*)."""

    registry: str = ""  # registry base URL ("" = standalone/test)
    max_lag: int = 8  # K: delta ring depth; >K behind -> full pull
    compress: str = "fp8"  # delta wire mode: off | fp8 | int8
    poll_s: float = 0.05  # worker poll interval
    drain_on: str = "warn"  # health state that drains a source
    port: int = 0  # worker HTTP port (0 = ephemeral)
    timeout_s: float = 15.0  # per-pull / per-RPC deadline

    @classmethod
    def from_env(cls, **overrides: Any) -> "ServeConfig":
        def _pick(env: str, key: str, cast: Callable[[str], Any]) -> Any:
            if key in overrides and overrides[key] is not None:
                return overrides[key]
            raw = os.environ.get(env)
            if raw is None or not raw.strip():
                return getattr(cls, key) if key != "registry" else ""
            try:
                return cast(raw.strip())
            except (TypeError, ValueError) as e:
                raise ValueError(f"bad {env}={raw!r}: {e}") from e

        cfg = cls(
            registry=_pick(SERVE_REGISTRY_ENV, "registry", str),
            max_lag=_pick(SERVE_MAX_LAG_ENV, "max_lag", int),
            compress=_pick(SERVE_COMPRESS_ENV, "compress", str),
            poll_s=_pick(SERVE_POLL_S_ENV, "poll_s", float),
            drain_on=_pick(SERVE_DRAIN_ON_ENV, "drain_on", str),
            port=_pick(SERVE_PORT_ENV, "port", int),
            timeout_s=_pick(SERVE_TIMEOUT_S_ENV, "timeout_s", float),
        )
        cfg.validate()
        return cfg

    def validate(self) -> None:
        self.compress = str(self.compress).strip().lower()
        self.drain_on = str(self.drain_on).strip().lower()
        if self.compress not in COMPRESS_MODES:
            raise ValueError(
                f"invalid {SERVE_COMPRESS_ENV}={self.compress!r}: "
                f"expected one of {COMPRESS_MODES}"
            )
        if self.drain_on not in _DRAIN_POLICIES:
            raise ValueError(
                f"invalid {SERVE_DRAIN_ON_ENV}={self.drain_on!r}: "
                f"expected one of {_DRAIN_POLICIES}"
            )
        if self.max_lag < 1:
            raise ValueError(f"invalid {SERVE_MAX_LAG_ENV}={self.max_lag}: must be >= 1")
        if self.poll_s <= 0:
            raise ValueError(f"invalid {SERVE_POLL_S_ENV}={self.poll_s}: must be > 0")
        if self.timeout_s <= 0:
            raise ValueError(
                f"invalid {SERVE_TIMEOUT_S_ENV}={self.timeout_s}: must be > 0"
            )

    def to_json(self) -> Dict[str, Any]:
        return {
            "registry": self.registry,
            "max_lag": self.max_lag,
            "compress": self.compress,
            "poll_s": self.poll_s,
            "drain_on": self.drain_on,
            "port": self.port,
            "timeout_s": self.timeout_s,
        }


# --------------------------------------------------------------------------
# Fault hook (event_injector glue, mirrors coordination.set_rpc_fault_hook)
# --------------------------------------------------------------------------
_fault_hook: Optional[Callable[[str, Dict[str, Any]], Optional[str]]] = None
_fault_lock = threading.Lock()


def set_serve_fault_hook(
    fn: Optional[Callable[[str, Dict[str, Any]], Optional[str]]],
) -> None:
    """Install a process-wide serving fault hook (test-only).

    ``fn(event, info)`` fires at: ``"announce"`` (publisher announced a
    version), ``"delta_request"`` (a delta is about to be served),
    ``"worker_pull"`` (a worker is about to poll/pull).  Returning
    ``"die"`` from a serve-side event drops the connection; the hook may
    also sleep (pull delays) or call back into the harness (kills)."""
    global _fault_hook
    with _fault_lock:
        _fault_hook = fn


def _fire_fault(event: str, info: Dict[str, Any]) -> Optional[str]:
    with _fault_lock:
        fn = _fault_hook
    if fn is None:
        return None
    try:
        return fn(event, info)
    except Exception:  # noqa: BLE001 — a broken hook must not break serving
        logger.exception("serve fault hook failed on %s", event)
        return None


# --------------------------------------------------------------------------
# Flat-vector codec helpers
# --------------------------------------------------------------------------
def flatten_params(params: Any) -> Tuple[np.ndarray, Dict[str, Any]]:
    """Flatten a pytree (or flat array) of parameters into one contiguous
    f32 host vector plus a layout descriptor.

    Serving state is float32 end-to-end: every leaf is staged to host and
    cast, concatenated in tree-flatten order.  The layout (shapes +
    dtypes) rides along so mismatched sources are detected, not mixed."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not leaves:
        raise ValueError("cannot publish an empty parameter tree")
    flats: List[np.ndarray] = []
    layout_leaves: List[List[Any]] = []
    for leaf in leaves:
        host = np.asarray(leaf)
        layout_leaves.append([list(host.shape), str(host.dtype)])
        flats.append(np.ascontiguousarray(host, dtype=np.float32).ravel())
    flat = flats[0] if len(flats) == 1 else np.concatenate(flats)
    flat = np.ascontiguousarray(flat, dtype=np.float32)
    layout = {"n": int(flat.size), "leaves": layout_leaves}
    layout["sig"] = layout_signature(layout)
    return flat, layout


def layout_signature(layout: Dict[str, Any]) -> str:
    basis = {"n": layout["n"], "leaves": layout["leaves"]}
    return hashlib.sha1(
        json.dumps(basis, sort_keys=True).encode()
    ).hexdigest()[:12]


def encode_delta(delta: np.ndarray, mode: str) -> Any:
    """Encode a flat f32 delta for the wire (CompressedWire or raw bytes)."""
    if mode == "off":
        return np.ascontiguousarray(delta, dtype=np.float32).tobytes()
    return compress_bucket(
        np.ascontiguousarray(delta, dtype=np.float32), mode, dtype=np.float32
    )


def decode_delta(wire: Any, mode: str, n: int) -> np.ndarray:
    """Decode a wire delta back to a flat f32 vector of length ``n``.

    This is THE reference decode: the publisher replays it to advance its
    own error-feedback reference, so publisher and worker stay bitwise in
    lockstep by construction."""
    if mode == "off":
        out = np.frombuffer(wire, dtype=np.float32).copy()
    else:
        out = decompress_bucket(wire, dtype=np.float32)
    if out.size != n:
        raise ValueError(f"delta length {out.size} != layout n {n}")
    return out


def delta_nbytes(wire: Any) -> int:
    """Wire size of an encoded delta (payload + scales; raw bytes for off)."""
    if isinstance(wire, (bytes, bytearray, memoryview)):
        return len(wire)
    return int(wire.payload.nbytes + wire.scales.nbytes)


def answer_from_flat(flat: Optional[np.ndarray], seed: int) -> Optional[float]:
    """Deterministic toy inference: a strided dot over the parameter
    vector.  Pure function of (params, seed) so two workers at the same
    snapshot version answer bit-identically — the convergence check the
    chaos soak and bench both lean on."""
    if flat is None or flat.size == 0:
        return None
    n = int(flat.size)
    k = min(128, n)
    start = (int(seed) * 2654435761) % max(1, n - k + 1)
    window = flat[start : start + k].astype(np.float64)
    weights = np.cos(np.arange(k, dtype=np.float64) * 0.1)
    return float(np.dot(window, weights))


def _json_body(handler: BaseHTTPRequestHandler) -> Dict[str, Any]:
    length = int(handler.headers.get("Content-Length", 0) or 0)
    raw = handler.rfile.read(length) if length else b"{}"
    return json.loads(raw.decode() or "{}")


def _send_json(
    handler: BaseHTTPRequestHandler, code: int, obj: Dict[str, Any]
) -> None:
    body = json.dumps(obj).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def _send_bytes(handler: BaseHTTPRequestHandler, body: bytes) -> None:
    handler.send_response(200)
    handler.send_header("Content-Type", "application/octet-stream")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def _http_json(
    url: str,
    payload: Optional[Dict[str, Any]] = None,
    timeout: float = 5.0,
) -> Tuple[int, Dict[str, Any]]:
    """One JSON request; returns (status, body).  4xx bodies are parsed,
    not raised — the registry speaks structured 409s."""
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url,
        data=data,
        method="POST" if data is not None else "GET",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode() or "{}")
        except Exception:  # noqa: BLE001
            return e.code, {}


# --------------------------------------------------------------------------
# SnapshotRegistry — lives next to the lighthouse, health-gates routing
# --------------------------------------------------------------------------
class SnapshotRegistry:
    """Tracks which replicas can serve which snapshot version and orders
    them for workers, draining unhealthy sources first.

    Stale-instance protection reuses the aggregator ``(epoch, seq)``
    pattern: each registry instance mints a fresh ``epoch`` at startup;
    announcements carry the epoch the publisher registered under plus a
    per-publisher monotonic ``seq``.  After a registry (lighthouse)
    restart every old announcement is rejected with 409 ``stale_epoch``
    until the publisher re-registers — a replayed or delayed announce can
    never resurrect pre-restart state."""

    def __init__(
        self,
        lighthouse_addr: Optional[str] = None,
        health_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        drain_on: str = "warn",
        poll_s: float = 0.25,
        port: int = 0,
        host: str = "127.0.0.1",
    ) -> None:
        if drain_on not in _DRAIN_POLICIES:
            raise ValueError(
                f"drain_on must be one of {_DRAIN_POLICIES}, got {drain_on!r}"
            )
        self._lock = threading.Lock()
        self.epoch = uuid.uuid4().hex[:12]
        self._drain_on = drain_on
        self._poll_s = poll_s
        self._lighthouse_addr = lighthouse_addr
        self._health_fn = health_fn
        # replica_id -> {version, seq, full_url, delta_url, chain, ...}
        self._sources: Dict[str, Dict[str, Any]] = {}
        self._registered: Dict[str, str] = {}  # replica_id -> epoch granted
        self._drained_health: Dict[str, str] = {}  # replica_id -> state name
        self._drained_manual: set = set()
        self._counters: Dict[str, int] = {
            "announce_total": 0,
            "announce_rejected_total": 0,
            "drain_transitions_total": 0,
        }
        self._metrics = MetricsRegistry()
        self._stop = threading.Event()

        registry = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: Any) -> None:
                logger.debug("serve_registry: " + fmt, *args)

            def do_GET(self) -> None:  # noqa: N802 — http.server API
                try:
                    path = self.path.partition("?")[0]
                    if path == "/serve/sources":
                        _send_json(self, 200, registry.sources())
                    elif path == "/serve/status":
                        _send_json(self, 200, registry.status())
                    elif path in ("/metrics", "/"):
                        registry._refresh_metrics()
                        body = registry._metrics.render().encode()
                        self.send_response(200)
                        self.send_header(
                            "Content-Type", "text/plain; version=0.0.4"
                        )
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    else:
                        self.send_error(404)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001
                    logger.exception("serve_registry GET failed")
                    try:
                        self.send_error(500, str(e))
                    except Exception:  # noqa: BLE001
                        pass

            def do_POST(self) -> None:  # noqa: N802 — http.server API
                try:
                    path = self.path.partition("?")[0]
                    body = _json_body(self)
                    if path == "/serve/register":
                        code, resp = registry.register(str(body["replica_id"]))
                    elif path == "/serve/announce":
                        code, resp = registry.announce(body)
                    elif path == "/serve/drain":
                        code, resp = registry.drain(
                            str(body["replica_id"]),
                            bool(body.get("drain", True)),
                        )
                    else:
                        self.send_error(404)
                        return
                    _send_json(self, code, resp)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001
                    logger.exception("serve_registry POST failed")
                    try:
                        self.send_error(500, str(e))
                    except Exception:  # noqa: BLE001
                        pass

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever,
            daemon=True,
            name="torchft_serve_registry",
        )
        self._serve_thread.start()
        self._poll_thread: Optional[threading.Thread] = None
        if lighthouse_addr or health_fn is not None:
            self._poll_thread = threading.Thread(
                target=self._health_poll_loop,
                daemon=True,
                name="torchft_serve_registry_health",
            )
            self._poll_thread.start()

    # -- public api --------------------------------------------------------
    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def register(self, replica_id: str) -> Tuple[int, Dict[str, Any]]:
        with self._lock:
            self._registered[replica_id] = self.epoch
            return 200, {"epoch": self.epoch}

    def announce(self, body: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        try:
            replica_id = str(body["replica_id"])
            epoch = str(body["epoch"])
            seq = int(body["seq"])
            version: Version = (int(body["quorum_id"]), int(body["step"]))
            full_url = str(body["full_url"])
            delta_url = str(body["delta_url"])
            chain = str(body["chain"])
        except (KeyError, TypeError, ValueError) as e:
            return 400, {"error": f"malformed announce: {e}"}
        with self._lock:
            self._counters["announce_total"] += 1
            if epoch != self.epoch:
                # pre-restart publisher: force a re-register handshake so
                # stale announcements can't resurrect old state
                self._counters["announce_rejected_total"] += 1
                return 409, {"error": "stale_epoch", "epoch": self.epoch}
            prior = self._sources.get(replica_id)
            if prior is not None and seq <= prior["seq"]:
                self._counters["announce_rejected_total"] += 1
                return 409, {"error": "stale_seq", "have_seq": prior["seq"]}
            if prior is not None and version <= tuple(prior["version"]):
                # snapshot versions are strictly monotone per replica —
                # a reconfigure bumps quorum_id, never rewinds the pair
                self._counters["announce_rejected_total"] += 1
                return 409, {
                    "error": "stale_version",
                    "have": list(prior["version"]),
                }
            self._sources[replica_id] = {
                "version": list(version),
                "seq": seq,
                "full_url": full_url,
                "delta_url": delta_url,
                "chain": chain,
                "announced_at": time.time(),
            }
            return 200, {"ok": True, "latest": self._latest_locked()}

    def drain(self, replica_id: str, drain: bool) -> Tuple[int, Dict[str, Any]]:
        with self._lock:
            before = replica_id in self._drained_manual
            if drain:
                self._drained_manual.add(replica_id)
            else:
                self._drained_manual.discard(replica_id)
            if before != drain:
                self._counters["drain_transitions_total"] += 1
            return 200, {"ok": True, "draining": sorted(self._all_drained_locked())}

    def forget(self, replica_id: str) -> None:
        with self._lock:
            self._sources.pop(replica_id, None)
            self._registered.pop(replica_id, None)

    def sources(self) -> Dict[str, Any]:
        """Ordered source list for workers: healthy sources first (newest
        version wins ties), drained sources kept at the TAIL — a fully
        drained fleet still serves rather than failing requests."""
        with self._lock:
            drained = self._all_drained_locked()
            entries = []
            for rid, src in self._sources.items():
                entries.append(
                    {
                        "replica_id": rid,
                        "version": list(src["version"]),
                        "full_url": src["full_url"],
                        "delta_url": src["delta_url"],
                        "chain": src["chain"],
                        "draining": rid in drained,
                    }
                )
            entries.sort(
                key=lambda e: (
                    e["draining"],
                    [-e["version"][0], -e["version"][1]],
                    e["replica_id"],
                )
            )
            latest = self._latest_locked()
            chain = None
            if latest is not None:
                for e in entries:
                    if e["version"] == latest:
                        chain = e["chain"]
                        break
            return {
                "epoch": self.epoch,
                "latest": latest,
                "chain": chain,
                "sources": entries,
                "draining": sorted(drained),
            }

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "epoch": self.epoch,
                "drain_on": self._drain_on,
                "sources": dict(self._sources),
                "drained_health": dict(self._drained_health),
                "drained_manual": sorted(self._drained_manual),
                "counters": dict(self._counters),
            }

    def shutdown(self) -> None:
        self._stop.set()
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass

    # -- internals ---------------------------------------------------------
    def _all_drained_locked(self) -> set:
        return set(self._drained_health) | self._drained_manual

    def _latest_locked(self) -> Optional[List[int]]:
        best: Optional[List[int]] = None
        drained = self._all_drained_locked()
        pool = [
            src["version"]
            for rid, src in self._sources.items()
            if rid not in drained
        ] or [src["version"] for src in self._sources.values()]
        for v in pool:
            if best is None or tuple(v) > tuple(best):
                best = v
        return best

    def _health_poll_loop(self) -> None:
        while not self._stop.wait(self._poll_s):
            try:
                health = self._poll_health()
            except Exception:  # noqa: BLE001 — keep serving on poll failure
                logger.debug("serve_registry health poll failed", exc_info=True)
                continue
            if health is None:
                continue
            self.apply_health(health)

    def _poll_health(self) -> Optional[Dict[str, Any]]:
        if self._health_fn is not None:
            return self._health_fn()
        from .coordination import LighthouseClient  # lazy: avoid import cycle

        assert self._lighthouse_addr is not None
        return LighthouseClient(
            self._lighthouse_addr, connect_timeout=2.0
        ).health()

    def apply_health(self, health: Dict[str, Any]) -> None:
        """Fold one /health summary into the drain set.  Split out from the
        poll loop so tests can drive escalations deterministically."""
        from .healthwatch import serving_eligible

        replicas = health.get("replicas", {}) or {}
        with self._lock:
            next_drained: Dict[str, str] = {}
            for rid, info in replicas.items():
                state = info.get("state", "ok")
                if not serving_eligible(state, drain_on=self._drain_on):
                    next_drained[rid] = str(state)
            # replicas the lighthouse has excluded may vanish from the
            # replicas map entirely; keep them drained
            for rid in health.get("excluded", []) or []:
                next_drained.setdefault(str(rid), "excluded")
            if set(next_drained) != set(self._drained_health):
                self._counters["drain_transitions_total"] += 1
                logger.info(
                    "serve_registry drain set -> %s", sorted(next_drained)
                )
            self._drained_health = next_drained

    def _refresh_metrics(self) -> None:
        with self._lock:
            drained = self._all_drained_locked()
            latest = self._latest_locked()
            n_sources = len(self._sources)
            counters = dict(self._counters)
        m = self._metrics
        m.gauge_set(
            "serve_draining", float(len(drained)),
            "Sources currently drained from the serving set."
        )
        m.gauge_set(
            "serve_sources", float(n_sources),
            "Sources announced to the snapshot registry."
        )
        m.gauge_set(
            "serve_latest_step",
            float(latest[1]) if latest else -1.0,
            "Step of the newest announced snapshot.",
        )
        for name, val in counters.items():
            m.counter_set(f"serve_registry_{name}", float(val))


# --------------------------------------------------------------------------
# RegistryClient — retrying JSON client used by publishers and workers
# --------------------------------------------------------------------------
class RegistryClient:
    """Thin retrying client for the registry's JSON API.

    Transport errors retry under the standard TORCHFT_RETRY_* policy;
    structured 4xx answers (stale_epoch & friends) are returned to the
    caller, not retried — they are protocol, not weather."""

    def __init__(
        self,
        base_url: str,
        timeout: float = 5.0,
        retry_policy: Optional[RetryPolicy] = None,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self._timeout = timeout
        self._policy = (
            retry_policy if retry_policy is not None else RetryPolicy.from_env()
        )

    def _call(
        self, path: str, payload: Optional[Dict[str, Any]] = None
    ) -> Tuple[int, Dict[str, Any]]:
        def attempt(remaining: float) -> Tuple[int, Dict[str, Any]]:
            return _http_json(
                f"{self.base_url}{path}",
                payload,
                timeout=min(self._timeout, max(remaining, 0.05)),
            )

        return retry_call(
            attempt,
            policy=self._policy,
            timeout=self._timeout,
            retryable=(OSError, TimeoutError, ConnectionError, ValueError),
        )

    def register(self, replica_id: str) -> str:
        code, resp = self._call("/serve/register", {"replica_id": replica_id})
        if code != 200:
            raise RuntimeError(f"register failed: {code} {resp}")
        return str(resp["epoch"])

    def announce(self, body: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        return self._call("/serve/announce", body)

    def sources(self) -> Dict[str, Any]:
        code, resp = self._call("/serve/sources")
        if code != 200:
            raise RuntimeError(f"sources failed: {code} {resp}")
        return resp

    def drain(self, replica_id: str, drain: bool = True) -> Dict[str, Any]:
        code, resp = self._call(
            "/serve/drain", {"replica_id": replica_id, "drain": drain}
        )
        if code != 200:
            raise RuntimeError(f"drain failed: {code} {resp}")
        return resp


# --------------------------------------------------------------------------
# SnapshotPublisher — rides the commit path on each live replica
# --------------------------------------------------------------------------
class SnapshotPublisher:
    """Publishes versioned parameter snapshots from one training replica.

    Full snapshots are staged on the existing checkpoint transport (the
    same ranged/resumable wire heals ride); per-step deltas are encoded
    once and retained in a ring of the last ``max_lag`` versions.  The
    error-feedback reference ``R`` (class docstring above) is what full
    pulls serve, so delta walks and full pulls are bitwise-identical."""

    def __init__(
        self,
        replica_id: str,
        config: Optional[ServeConfig] = None,
        registry_url: Optional[str] = None,
        hostname: str = "127.0.0.1",
    ) -> None:
        from .checkpointing.http_transport import HTTPTransport

        self.replica_id = replica_id
        self.cfg = config if config is not None else ServeConfig.from_env()
        url = registry_url if registry_url is not None else self.cfg.registry
        self._registry = RegistryClient(url, timeout=self.cfg.timeout_s) if url else None
        self._epoch: Optional[str] = None
        self._seq = 0
        self._lock = threading.Lock()
        self._ref: Optional[np.ndarray] = None
        self._version: Optional[Version] = None
        self._layout: Optional[Dict[str, Any]] = None
        self._chain: Optional[str] = None
        self._deltas: "OrderedDict[Version, bytes]" = OrderedDict()
        self.counters: Dict[str, int] = {
            "published_total": 0,
            "bootstrap_pulls_total": 0,
            "announce_rejected_total": 0,
            "delta_bytes_total": 0,
        }
        self._killed = False

        # full snapshots ride the resumable checkpoint transport verbatim
        self._transport = HTTPTransport(
            timeout=self.cfg.timeout_s, hostname=hostname
        )

        publisher = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: Any) -> None:
                logger.debug("serve_publisher: " + fmt, *args)

            def do_GET(self) -> None:  # noqa: N802 — http.server API
                try:
                    path = self.path.partition("?")[0]
                    parts = path.strip("/").split("/")
                    # /serve/delta/{quorum_id}/{step} | /serve/manifest
                    if parts[:2] == ["serve", "manifest"]:
                        _send_json(self, 200, publisher.manifest())
                        return
                    if len(parts) == 4 and parts[:2] == ["serve", "delta"]:
                        version = (int(parts[2]), int(parts[3]))
                        action = _fire_fault(
                            "delta_request",
                            {
                                "replica_id": publisher.replica_id,
                                "version": version,
                            },
                        )
                        if action == "die":
                            self.close_connection = True
                            return
                        blob = publisher.delta_blob(version)
                        if blob is None:
                            self.send_error(404, "delta not retained")
                            return
                        _send_bytes(self, blob)
                        return
                    self.send_error(404)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001
                    logger.exception("serve_publisher GET failed")
                    try:
                        self.send_error(500, str(e))
                    except Exception:  # noqa: BLE001
                        pass

        self._delta_server = ThreadingHTTPServer((hostname, 0), _Handler)
        self._delta_server.daemon_threads = True
        self._delta_thread = threading.Thread(
            target=self._delta_server.serve_forever,
            daemon=True,
            name="torchft_serve_publisher",
        )
        self._delta_thread.start()

        # async publish: the commit path hands off a host copy and returns
        self._queue_lock = threading.Lock()
        self._queue_item: Optional[Tuple[int, int, np.ndarray, Dict[str, Any]]] = None
        self._queue_event = threading.Event()
        self._stop = threading.Event()
        self._worker = threading.Thread(
            target=self._publish_loop, daemon=True,
            name="torchft_serve_publish",
        )
        self._worker.start()

    # -- addresses ---------------------------------------------------------
    @property
    def full_url(self) -> str:
        return self._transport.metadata()

    @property
    def delta_url(self) -> str:
        host, port = self._delta_server.server_address[:2]
        return f"http://{host}:{port}"

    @property
    def version(self) -> Optional[Version]:
        with self._lock:
            return self._version

    @property
    def chain(self) -> Optional[str]:
        with self._lock:
            return self._chain

    def ref_flat(self) -> Optional[np.ndarray]:
        with self._lock:
            return None if self._ref is None else self._ref.copy()

    def manifest(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "replica_id": self.replica_id,
                "chain": self._chain,
                "mode": self.cfg.compress,
                "version": list(self._version) if self._version else None,
                "layout_sig": self._layout["sig"] if self._layout else None,
                "deltas": [list(v) for v in self._deltas.keys()],
            }

    def delta_blob(self, version: Version) -> Optional[bytes]:
        with self._lock:
            return self._deltas.get(tuple(version))

    # -- publishing --------------------------------------------------------
    def publish(self, quorum_id: int, step: int, params: Any) -> Optional[Version]:
        """Synchronously publish one committed snapshot.  Returns the
        published version, or None when the version was already covered
        (another replica got there first after a bootstrap)."""
        flat, layout = flatten_params(params)
        return self._publish_flat(int(quorum_id), int(step), flat, layout)

    def publish_async(self, quorum_id: int, step: int, params: Any) -> None:
        """Commit-path entry: snapshot the params to host NOW (so the next
        step cannot tear them), encode+announce on the publisher thread.
        Keeps only the newest pending item — the delta chain's ``prev``
        pointers make skipped versions safe for delta walkers."""
        flat, layout = flatten_params(params)
        with self._queue_lock:
            self._queue_item = (int(quorum_id), int(step), flat, layout)
        self._queue_event.set()

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait until the async queue is drained (tests/benches)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._queue_lock:
                idle = self._queue_item is None
            if idle and not self._queue_event.is_set():
                return True
            time.sleep(0.005)
        return False

    def _publish_loop(self) -> None:
        while not self._stop.is_set():
            self._queue_event.wait(0.1)
            if self._stop.is_set():
                return
            with self._queue_lock:
                item = self._queue_item
                self._queue_item = None
                if item is None:
                    self._queue_event.clear()
                    continue
            try:
                self._publish_flat(*item)
            except Exception:  # noqa: BLE001 — advisory plane must not die
                logger.exception("async snapshot publish failed")

    def _publish_flat(
        self,
        quorum_id: int,
        step: int,
        flat: np.ndarray,
        layout: Dict[str, Any],
    ) -> Optional[Version]:
        version: Version = (quorum_id, step)
        with self._lock:
            if self._killed:
                return None
            if self._layout is not None and layout["sig"] != self._layout["sig"]:
                # model surgery: deltas cannot bridge layouts — reset the
                # chain, workers will full-pull
                logger.warning(
                    "parameter layout changed (%s -> %s); resetting serve chain",
                    self._layout["sig"], layout["sig"],
                )
                self._ref = None
                self._version = None
                self._chain = None
                self._deltas.clear()
            self._layout = layout

        # a publisher that is behind the registry (fresh, healed, or it
        # missed commits while ejected) must re-seat its reference on the
        # fleet's published state or its deltas would fork the chain
        self._maybe_bootstrap(version, layout)

        with self._lock:
            if self._killed:
                return None
            if self._version is not None and version <= self._version:
                return None  # already covered (bootstrap adopted >= version)
            if self._chain is None:
                # deterministic chain id: replicas racing to seed the chain
                # from identical state mint identical ids, so either one's
                # deltas extend the other's
                self._chain = (
                    f"{self.cfg.compress}-{layout['sig']}-{quorum_id}.{step}"
                )
                self._ref = np.zeros(layout["n"], dtype=np.float32)
                self._version = None
            assert self._ref is not None
            prev = self._version
            delta = flat - self._ref
            wire = encode_delta(delta, self.cfg.compress)
            decoded = decode_delta(wire, self.cfg.compress, layout["n"])
            # replay our own decode: R_v = R_{v-1} + decode(delta_v) is the
            # exact arithmetic every worker performs
            new_ref = self._ref + decoded
            record = {
                "v": 1,
                "chain": self._chain,
                "quorum_id": quorum_id,
                "step": step,
                "prev": list(prev) if prev is not None else None,
                "mode": self.cfg.compress,
                "layout_sig": layout["sig"],
                "n": layout["n"],
                "wire": wire,
            }
            blob = pickle.dumps(record, protocol=pickle.HIGHEST_PROTOCOL)
            self._ref = new_ref
            self._version = version
            self._deltas[version] = blob
            while len(self._deltas) > self.cfg.max_lag:
                self._deltas.popitem(last=False)
            self.counters["published_total"] += 1
            self.counters["delta_bytes_total"] += delta_nbytes(record["wire"])
            ref_to_stage = self._ref
            meta = {
                "quorum_id": quorum_id,
                "step": step,
                "chain": self._chain,
                "mode": self.cfg.compress,
                "layout": json.dumps(layout),
            }

        # stage the full snapshot on the heal transport (dst_ranks=[]: the
        # serving window is pull-based and never force-closed here)
        self._transport.send_checkpoint(
            dst_ranks=[],
            step=step,
            state_dict={"flat": ref_to_stage, "meta": meta},
            timeout=self.cfg.timeout_s,
        )
        self._announce(version)
        _fire_fault(
            "announce",
            {
                "replica_id": self.replica_id,
                "version": version,
                "publisher": self,
            },
        )
        return version

    def _maybe_bootstrap(self, version: Version, layout: Dict[str, Any]) -> None:
        if self._registry is None:
            return
        try:
            listing = self._registry.sources()
        except Exception:  # noqa: BLE001 — registry down: publish standalone
            logger.debug("registry sources unavailable", exc_info=True)
            return
        latest = listing.get("latest")
        if latest is None:
            return
        latest_v: Version = (int(latest[0]), int(latest[1]))
        with self._lock:
            ours = self._version
            chain = self._chain
        if ours is not None and chain == listing.get("chain"):
            if ours >= latest_v:
                return  # we are the tip (or beyond): delta normally
            if latest_v == version:
                # a co-replica just published the version WE are about to
                # publish, and nothing was published strictly between our
                # ref and it — our delta is byte-identical to theirs (same
                # prev, same committed params, same deterministic codec),
                # so publishing extends the chain without re-seating
                return
        others = [
            s for s in listing.get("sources", [])
            if s["replica_id"] != self.replica_id
        ]
        if not others:
            return  # registry only knows us; nothing to re-seat on
        try:
            flat, meta = pull_full_snapshot(
                others, latest_v, timeout=self.cfg.timeout_s
            )
        except Exception:  # noqa: BLE001
            logger.warning(
                "serve bootstrap pull failed; starting a fresh chain",
                exc_info=True,
            )
            with self._lock:
                self._ref = None
                self._version = None
                self._chain = None
                self._deltas.clear()
            return
        got_layout = json.loads(meta["layout"])
        with self._lock:
            if got_layout["sig"] != layout["sig"] or meta["mode"] != self.cfg.compress:
                # incompatible fleet state: publish a fresh chain instead
                self._ref = None
                self._version = None
                self._chain = None
                self._deltas.clear()
                return
            self._ref = np.ascontiguousarray(flat, dtype=np.float32)
            self._version = (int(meta["quorum_id"]), int(meta["step"]))
            self._chain = meta["chain"]
            self._deltas.clear()  # our old ring forked from a stale ref
            self.counters["bootstrap_pulls_total"] += 1

    def _announce(self, version: Version) -> None:
        if self._registry is None:
            return
        for attempt in range(2):
            try:
                if self._epoch is None:
                    self._epoch = self._registry.register(self.replica_id)
                self._seq += 1
                code, resp = self._registry.announce(
                    {
                        "replica_id": self.replica_id,
                        "epoch": self._epoch,
                        "seq": self._seq,
                        "quorum_id": version[0],
                        "step": version[1],
                        "full_url": self.full_url,
                        "delta_url": self.delta_url,
                        "chain": self.chain,
                    }
                )
            except Exception:  # noqa: BLE001 — registry down: serve anyway
                logger.warning("snapshot announce failed", exc_info=True)
                return
            if code == 200:
                return
            if resp.get("error") == "stale_epoch" and attempt == 0:
                # registry (lighthouse) restarted: re-register under the
                # new epoch and replay the announce once
                self._epoch = None
                self._seq = 0
                continue
            self.counters["announce_rejected_total"] += 1
            logger.info("announce rejected: %s", resp)
            return

    # -- lifecycle ---------------------------------------------------------
    def kill(self) -> None:
        """Chaos hook: die abruptly — both serve endpoints vanish, nothing
        is deregistered (the registry learns via health/drain)."""
        with self._lock:
            self._killed = True
        self._stop.set()
        self._queue_event.set()
        for srv in (self._delta_server,):
            try:
                srv.shutdown()
                srv.server_close()
            except Exception:  # noqa: BLE001
                pass
        try:
            self._transport.shutdown(wait=False)
        except Exception:  # noqa: BLE001
            pass

    def shutdown(self) -> None:
        self.kill()


# --------------------------------------------------------------------------
# Full-pull client helper (shared by workers and bootstrapping publishers)
# --------------------------------------------------------------------------
def pull_full_snapshot(
    sources: List[Dict[str, Any]],
    version: Version,
    timeout: float = 15.0,
    on_event: Optional[Callable[..., None]] = None,
) -> Tuple[np.ndarray, Dict[str, Any]]:
    """Ranged, resumable, multi-source full pull of snapshot ``version``.

    Rides ``HTTPTransport.recv_checkpoint_multi`` verbatim: byte-range
    chunks, crc32 trailers, mid-transfer failover across the registry's
    ordered source list.  Returns ``(flat_f32, meta)``; raises if every
    source is exhausted."""
    from .checkpointing.http_transport import HTTPTransport

    if not sources:
        raise RuntimeError("no snapshot sources available")
    receiver = HTTPTransport(timeout=timeout, client_only=True)
    pairs = [
        (s["replica_id"], (lambda u=s["full_url"]: u)) for s in sources
    ]
    state = receiver.recv_checkpoint_multi(
        pairs, step=version[1], timeout=timeout, on_event=on_event
    )
    timings = receiver.last_recv_timings()
    flat = np.ascontiguousarray(state["flat"], dtype=np.float32)
    meta = dict(state["meta"])
    meta["_bytes"] = int(timings.total_bytes) if timings else flat.nbytes
    meta["_failovers"] = int(timings.failovers) if timings else 0
    got = (int(meta["quorum_id"]), int(meta["step"]))
    if got < version:
        raise RuntimeError(
            f"stale full snapshot: asked {version}, sources serve {got}"
        )
    return flat, meta


# --------------------------------------------------------------------------
# ServeWorker — answers traffic from the last-applied snapshot
# --------------------------------------------------------------------------
class ServeWorker:
    """One inference worker: pulls snapshots in the background, answers
    ``/infer`` from the last-applied version under a local lock.

    The request path never touches the network, so registry convergence,
    source kills, and quorum reconfigurations cannot fail a request —
    the worker just answers from the version it has."""

    def __init__(
        self,
        registry_url: str,
        config: Optional[ServeConfig] = None,
        name: Optional[str] = None,
        start: bool = True,
    ) -> None:
        self.cfg = config if config is not None else ServeConfig.from_env()
        self.name = name or f"worker-{uuid.uuid4().hex[:6]}"
        self._registry = RegistryClient(registry_url, timeout=self.cfg.timeout_s)
        self._lock = threading.Lock()
        self._flat: Optional[np.ndarray] = None
        self._version: Optional[Version] = None
        self._chain: Optional[str] = None
        self._latest_seen: Optional[Version] = None
        self.counters: Dict[str, int] = {
            "requests_total": 0,
            "full_pulls_total": 0,
            "delta_pulls_total": 0,
            "full_bytes_total": 0,
            "delta_bytes_total": 0,
            "pull_failovers_total": 0,
            "pull_errors_total": 0,
        }
        self._metrics = MetricsRegistry()
        self._stop = threading.Event()

        worker = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: Any) -> None:
                logger.debug("serve_worker: " + fmt, *args)

            def do_GET(self) -> None:  # noqa: N802 — http.server API
                try:
                    raw_path, _, raw_query = self.path.partition("?")
                    if raw_path == "/infer":
                        q = urllib.parse.parse_qs(raw_query)
                        seed = int(q.get("seed", ["0"])[0])
                        _send_json(self, 200, worker.answer(seed))
                    elif raw_path == "/status":
                        _send_json(self, 200, worker.status())
                    elif raw_path in ("/metrics", "/"):
                        worker._refresh_metrics()
                        body = worker._metrics.render().encode()
                        self.send_response(200)
                        self.send_header(
                            "Content-Type", "text/plain; version=0.0.4"
                        )
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    else:
                        self.send_error(404)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001
                    # the request plane must answer, not error: fall back
                    # to a minimal degraded body if even answer() raised
                    logger.exception("serve_worker request failed")
                    try:
                        _send_json(self, 200, {"result": None, "error": str(e)})
                    except Exception:  # noqa: BLE001
                        pass

        self._server = ThreadingHTTPServer(("127.0.0.1", self.cfg.port), _Handler)
        self._server.daemon_threads = True
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever,
            daemon=True,
            name=f"torchft_serve_{self.name}",
        )
        self._serve_thread.start()

        self._pull_thread = threading.Thread(
            target=self._pull_loop, daemon=True,
            name=f"torchft_pull_{self.name}",
        )
        if start:
            self._pull_thread.start()

    # -- request path ------------------------------------------------------
    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    @property
    def version(self) -> Optional[Version]:
        with self._lock:
            return self._version

    def params_flat(self) -> Optional[np.ndarray]:
        with self._lock:
            return None if self._flat is None else self._flat.copy()

    def answer(self, seed: int) -> Dict[str, Any]:
        with self._lock:
            flat = self._flat
            version = self._version
            self.counters["requests_total"] += 1
        return {
            "result": answer_from_flat(flat, seed),
            "version": list(version) if version else None,
            "worker": self.name,
        }

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "worker": self.name,
                "version": list(self._version) if self._version else None,
                "latest_seen": (
                    list(self._latest_seen) if self._latest_seen else None
                ),
                "chain": self._chain,
                "lag_steps": self._lag_locked(),
                "counters": dict(self.counters),
            }

    def wait_version(self, version: Version, timeout: float = 10.0) -> bool:
        """Block until the worker has applied ``version`` or newer."""
        deadline = time.monotonic() + timeout
        target = tuple(version)
        while time.monotonic() < deadline:
            v = self.version
            if v is not None and tuple(v) >= target:
                return True
            time.sleep(0.01)
        return False

    def _lag_locked(self) -> int:
        if self._latest_seen is None:
            return 0
        if self._version is None:
            return self._latest_seen[1] + 1
        return max(0, self._latest_seen[1] - self._version[1])

    # -- pull plane --------------------------------------------------------
    def _pull_loop(self) -> None:
        while not self._stop.wait(self.cfg.poll_s):
            try:
                self.pull_once()
            except Exception:  # noqa: BLE001 — keep answering regardless
                with self._lock:
                    self.counters["pull_errors_total"] += 1
                logger.debug("worker pull failed", exc_info=True)

    def pull_once(self) -> bool:
        """One poll+pull cycle; returns True when a new version applied.
        Public so tests can drive the worker deterministically."""
        _fire_fault("worker_pull", {"worker": self.name})
        listing = self._registry.sources()
        latest = listing.get("latest")
        if latest is None:
            return False
        latest_v: Version = (int(latest[0]), int(latest[1]))
        chain = listing.get("chain")
        with self._lock:
            self._latest_seen = latest_v
            current = self._version
            cur_chain = self._chain
        if current is not None and current >= latest_v and cur_chain == chain:
            return False
        sources = [s for s in listing.get("sources", []) if s["chain"] == chain]
        if not sources:
            return False
        need_full = (
            current is None
            or cur_chain != chain
            or (latest_v[1] - current[1]) > self.cfg.max_lag
        )
        if not need_full:
            applied = self._delta_walk(sources, current, latest_v, chain)
            if applied:
                return True
            # chain gap (pruned ring / missed prev): fall back to full
        return self._full_pull(sources, latest_v)

    def _full_pull(self, sources: List[Dict[str, Any]], latest_v: Version) -> bool:
        def on_event(kind: str, **fields: Any) -> None:
            if kind == "heal_failover":
                with self._lock:
                    self.counters["pull_failovers_total"] += 1

        flat, meta = pull_full_snapshot(
            sources, latest_v, timeout=self.cfg.timeout_s, on_event=on_event
        )
        version: Version = (int(meta["quorum_id"]), int(meta["step"]))
        with self._lock:
            self._flat = flat
            self._version = version
            self._chain = meta["chain"]
            self.counters["full_pulls_total"] += 1
            self.counters["full_bytes_total"] += int(meta["_bytes"])
        logger.info(
            "%s full-pulled snapshot %s (%d bytes)",
            self.name, version, int(meta["_bytes"]),
        )
        return True

    def _delta_walk(
        self,
        sources: List[Dict[str, Any]],
        current: Version,
        latest_v: Version,
        chain: str,
    ) -> bool:
        """Apply per-step deltas current→latest, failing over across
        sources per fetch.  Deltas are chained by ``prev`` pointers (the
        previously *published* version, which may skip steps), so the walk
        asks each source's manifest which version extends ours."""
        applied_any = False
        guard = 0
        while True:
            guard += 1
            if guard > 4 * self.cfg.max_lag + 8:
                return applied_any  # defensive: malformed manifests
            with self._lock:
                cur = self._version
            if cur is None or cur >= latest_v:
                return applied_any
            record = self._fetch_next_delta(sources, cur, chain)
            if record is None:
                return False  # gap: caller falls back to full pull
            decoded = decode_delta(record["wire"], record["mode"], record["n"])
            version: Version = (int(record["quorum_id"]), int(record["step"]))
            with self._lock:
                if self._version is None or tuple(record["prev"]) != self._version:
                    return False  # raced: restart via full pull
                self._flat = self._flat + decoded
                self._version = version
                self.counters["delta_pulls_total"] += 1
                self.counters["delta_bytes_total"] += record["_bytes"]
            applied_any = True

    def _fetch_next_delta(
        self,
        sources: List[Dict[str, Any]],
        current: Version,
        chain: str,
    ) -> Optional[Dict[str, Any]]:
        """Find and fetch the delta whose ``prev`` pointer is ``current``,
        trying each source in registry order (failover per fetch)."""
        last_exc: Optional[Exception] = None
        for src in sources:
            base = src["delta_url"]
            try:
                with urllib.request.urlopen(
                    f"{base}/serve/manifest", timeout=self.cfg.timeout_s
                ) as r:
                    manifest = json.loads(r.read().decode())
                if manifest.get("chain") != chain:
                    continue
                # the publisher's ring is ordered oldest->newest; find the
                # record that extends our version
                versions = [tuple(v) for v in manifest.get("deltas", [])]
                nxt = None
                for v in versions:
                    if v > tuple(current):
                        blob_url = f"{base}/serve/delta/{v[0]}/{v[1]}"
                        with urllib.request.urlopen(
                            blob_url, timeout=self.cfg.timeout_s
                        ) as r:
                            blob = r.read()
                        record = pickle.loads(blob)
                        if (
                            record.get("chain") == chain
                            and record.get("prev") is not None
                            and tuple(record["prev"]) == tuple(current)
                        ):
                            record["_bytes"] = len(blob)
                            nxt = record
                        break  # only the first version past ours can chain
                if nxt is not None:
                    return nxt
            except Exception as e:  # noqa: BLE001 — next source
                last_exc = e
                with self._lock:
                    self.counters["pull_failovers_total"] += 1
                continue
        if last_exc is not None:
            logger.debug("delta fetch exhausted sources: %r", last_exc)
        return None

    def _refresh_metrics(self) -> None:
        with self._lock:
            version = self._version
            lag = self._lag_locked()
            counters = dict(self.counters)
        m = self._metrics
        m.gauge_set(
            "serve_version",
            float(version[1]) if version else -1.0,
            "Step of the last-applied snapshot.",
        )
        m.gauge_set(
            "serve_lag_steps", float(lag),
            "Steps between the newest announced snapshot and the applied one.",
        )
        m.counter_set(
            "serve_requests_total", float(counters["requests_total"]),
            "Inference requests answered.",
        )
        for name in (
            "full_pulls_total",
            "delta_pulls_total",
            "full_bytes_total",
            "delta_bytes_total",
            "pull_failovers_total",
            "pull_errors_total",
        ):
            m.counter_set(f"serve_{name}", float(counters[name]))

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if not self._pull_thread.is_alive():
            self._pull_thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass


# --------------------------------------------------------------------------
# CLI: python -m torchft_tpu.serving {worker|registry} ...
# --------------------------------------------------------------------------
def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m torchft_tpu.serving",
        description="torchft_tpu serving plane (docs/serving.md)",
    )
    sub = parser.add_subparsers(dest="role", required=True)

    w = sub.add_parser("worker", help="run one inference worker")
    w.add_argument(
        "--registry", default=None,
        help=f"registry URL (default: ${SERVE_REGISTRY_ENV})",
    )
    w.add_argument("--port", type=int, default=None, help="worker HTTP port")
    w.add_argument("--name", default=None)

    r = sub.add_parser("registry", help="run a standalone snapshot registry")
    r.add_argument("--lighthouse", default=None, help="lighthouse host:port")
    r.add_argument("--port", type=int, default=0)
    r.add_argument("--drain-on", default=None, choices=_DRAIN_POLICIES)

    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    if args.role == "worker":
        cfg = ServeConfig.from_env(
            registry=args.registry, port=args.port
        )
        if not cfg.registry:
            parser.error(
                f"--registry or ${SERVE_REGISTRY_ENV} is required for a worker"
            )
        worker = ServeWorker(cfg.registry, config=cfg, name=args.name)
        print(json.dumps({"worker": worker.name, "url": worker.url}), flush=True)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            worker.shutdown()
        return 0

    cfg = ServeConfig.from_env(drain_on=args.drain_on)
    registry = SnapshotRegistry(
        lighthouse_addr=args.lighthouse,
        drain_on=cfg.drain_on,
        port=args.port,
    )
    print(json.dumps({"registry": registry.url, "epoch": registry.epoch}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        registry.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
