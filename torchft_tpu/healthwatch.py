"""Healthwatch: straggler scoring + escalation policy for the health plane.

The quorum's health test was binary — a heartbeat is fresh or stale
(native/quorum.cc) — so a slow-but-alive replica (throttled TPU, degraded
NIC, noisy neighbor) silently drags every synchronous step: the managed
allreduce is a barrier, so the whole quorum runs at the straggler's pace.
Healthwatch turns the per-step telemetry the Manager already collects into
step-granular membership decisions:

1. The Manager publishes per-step telemetry (``step``, ``step_s``,
   ``wire_s``, heal/retry counters) which piggybacks on the existing
   heartbeat thread (no new RPC).
2. The lighthouse's native health ledger keeps a rolling window of
   compute-time samples per replica and scores each replica against the
   quorum median (:func:`straggler_scores`).
3. A policy engine escalates ``ok -> warn -> ejected -> probation -> ok``
   (:class:`HealthLedger`); an ejected replica enters the exclusion set the
   quorum computation consults, so ejection is just a step-granular
   membership change through the existing shrink path.
4. A replica that lost a chip and reshard onto its survivors
   (docs/operations.md#degraded-replicas) self-reports its reduced
   ``group_world_size`` in telemetry and enters ``DEGRADED``: its compute
   samples are capacity-scaled so the straggler statistics stay honest, it
   never accrues eject strikes, it drains from serving rotation, and it
   re-promotes to OK the moment full degree is reported again.

This module is the **canonical spec**: the native ledger
(native/healthwatch.cc) mirrors the math and state machine here, and
tests/test_healthwatch.py drives the same synthetic inputs through both
(via :func:`torchft_tpu.coordination.health_scores` /
:func:`~torchft_tpu.coordination.health_replay`) to pin them together.

Scoring
-------
Per replica, the robust statistic is the median of its window of
``step_s - wire_s`` samples (compute time: wall time equalizes across the
quorum because of the allreduce barrier — the straggler is the replica
with high compute and low wire wait). Across replicas the score is a
modified z-score: ``(x - median) / scale`` where ``scale`` is the MAD
rescaled by 0.6745, floored at ``rel_floor * median`` because the MAD
degenerates to zero on a homogeneous fleet (the straggler is the only
deviation, so the median of deviations vanishes). Only positive deviations
score — a fast replica is not a straggler. Fewer than two scorable
replicas -> no peer group -> all scores zero, which is also why 1- and
2-replica fleets can never reach the eject threshold organically.

Env knobs (all ``TORCHFT_HEALTH_*``)
------------------------------------
==========================  ========= =========================================
``TORCHFT_HEALTH_MODE``     observe   ``off`` | ``observe`` (score + report,
                                      never eject) | ``eject`` (opt-in)
``TORCHFT_HEALTH_WINDOW``   32        samples kept per replica
``TORCHFT_HEALTH_MIN_SAMPLES`` 5      warmup grace before a replica is scored
``TORCHFT_HEALTH_WARN_Z``   3.0       score above this -> warn
``TORCHFT_HEALTH_EJECT_Z``  6.0       score above this counts an eject strike
``TORCHFT_HEALTH_EJECT_STEPS`` 3      consecutive strikes before ejection
``TORCHFT_HEALTH_PROBATION_MS`` 10000 continuous fresh beats -> readmission
``TORCHFT_HEALTH_PROBE_OK`` 3         clean scored samples to leave probation
``TORCHFT_HEALTH_REL_FLOOR`` 0.05     scale floor as a fraction of the median
==========================  ========= =========================================
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Any, Dict, List, Mapping, Optional, Sequence

__all__ = [
    "HealthConfig",
    "HealthState",
    "HealthLedger",
    "median",
    "mad",
    "straggler_scores",
]

_MODES = ("off", "observe", "eject")


@dataclass(frozen=True)
class HealthConfig:
    """Healthwatch policy knobs; see the module docstring for semantics."""

    mode: str = "observe"
    window: int = 32
    min_samples: int = 5
    warn_z: float = 3.0
    eject_z: float = 6.0
    eject_steps: int = 3
    probation_ms: int = 10000
    probe_ok: int = 3
    rel_floor: float = 0.05

    @staticmethod
    def from_env() -> "HealthConfig":
        """Build from ``TORCHFT_HEALTH_*``; raises ValueError on junk."""
        defaults = HealthConfig()

        def _get(name: str, cast: Any, default: Any) -> Any:
            from torchft_tpu import knobs

            raw = knobs.env_raw(name)  # KeyError on unregistered names
            if raw is None or raw == "":
                return default
            try:
                return cast(raw)
            except (TypeError, ValueError) as e:
                raise ValueError(f"{name}={raw!r}: {e}") from e

        cfg = HealthConfig(
            mode=_get("TORCHFT_HEALTH_MODE", str, defaults.mode).lower(),
            window=_get("TORCHFT_HEALTH_WINDOW", int, defaults.window),
            min_samples=_get(
                "TORCHFT_HEALTH_MIN_SAMPLES", int, defaults.min_samples
            ),
            warn_z=_get("TORCHFT_HEALTH_WARN_Z", float, defaults.warn_z),
            eject_z=_get("TORCHFT_HEALTH_EJECT_Z", float, defaults.eject_z),
            eject_steps=_get(
                "TORCHFT_HEALTH_EJECT_STEPS", int, defaults.eject_steps
            ),
            probation_ms=_get(
                "TORCHFT_HEALTH_PROBATION_MS", int, defaults.probation_ms
            ),
            probe_ok=_get("TORCHFT_HEALTH_PROBE_OK", int, defaults.probe_ok),
            rel_floor=_get(
                "TORCHFT_HEALTH_REL_FLOOR", float, defaults.rel_floor
            ),
        )
        cfg.validate()
        return cfg

    def validate(self) -> None:
        if self.mode not in _MODES:
            raise ValueError(
                f"TORCHFT_HEALTH_MODE={self.mode!r}: must be one of {_MODES}"
            )
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if self.min_samples < 1:
            raise ValueError(
                f"min_samples must be >= 1, got {self.min_samples}"
            )
        if self.eject_z <= self.warn_z:
            raise ValueError(
                f"eject_z ({self.eject_z}) must be > warn_z ({self.warn_z}):"
                " an eject threshold at or below warn skips the warning"
                " escalation entirely"
            )
        if self.eject_steps < 1:
            raise ValueError(
                f"eject_steps must be >= 1, got {self.eject_steps}"
            )
        if self.probation_ms < 0:
            raise ValueError(
                f"probation_ms must be >= 0, got {self.probation_ms}"
            )
        if self.rel_floor <= 0:
            raise ValueError(
                f"rel_floor must be > 0, got {self.rel_floor}"
            )

    def to_json(self) -> Dict[str, Any]:
        """The dict shape the native lighthouse ctor takes as "health"."""
        return {
            "mode": self.mode,
            "window": self.window,
            "min_samples": self.min_samples,
            "warn_z": self.warn_z,
            "eject_z": self.eject_z,
            "eject_steps": self.eject_steps,
            "probation_ms": self.probation_ms,
            "probe_ok": self.probe_ok,
            "rel_floor": self.rel_floor,
        }


def median(values: Sequence[float]) -> float:
    """Median; 0.0 on empty input (matches the native ledger)."""
    if not values:
        return 0.0
    v = sorted(values)
    n = len(v)
    if n % 2 == 1:
        return float(v[n // 2])
    return 0.5 * (v[n // 2 - 1] + v[n // 2])


def mad(values: Sequence[float]) -> float:
    """Median absolute deviation around the median."""
    m = median(values)
    return median([abs(x - m) for x in values])


def straggler_scores(
    windows: Mapping[str, Sequence[float]], config: HealthConfig
) -> Dict[str, float]:
    """Quorum-relative straggler score per replica.

    ``windows`` maps replica_id -> rolling window of compute-time samples.
    Replicas with fewer than ``config.min_samples`` samples are in their
    warmup grace: scored 0 and excluded from the peer statistics. Fewer
    than two scorable replicas -> all zeros (no peer group).
    """
    scores: Dict[str, float] = {rid: 0.0 for rid in windows}
    stats = {
        rid: median(w)
        for rid, w in windows.items()
        if len(w) >= config.min_samples
    }
    if len(stats) < 2:
        return scores
    xs = list(stats.values())
    med = median(xs)
    scale = max(
        mad(xs) / 0.6745,
        config.rel_floor * max(med, 0.0),
        1e-9,
    )
    for rid, x in stats.items():
        scores[rid] = max(0.0, x - med) / scale  # only SLOW is unhealthy
    return scores


class HealthState(IntEnum):
    OK = 0
    WARN = 1
    EJECTED = 2
    PROBATION = 3
    # Escalation-wise DEGRADED sits between OK and WARN — slower than OK by
    # design, but never suspicious: the replica told us it lost a chip and
    # is running at reduced group degree (docs/operations.md#degraded-replicas).
    # The code is appended (not renumbered) because 0..3 are pinned by the
    # native ledger parity, timings() health_state, and /metrics docs.
    DEGRADED = 4


# Serving-plane drain policy (docs/serving.md): which health states pull a
# replica OUT of the snapshot-serving set.  ``"warn"`` (the default) drains
# at the first WARN strike — strictly BEFORE the warn→eject escalation
# removes the replica from training, so inference traffic never routes to
# a replica the ledger is already suspicious of.  ``"eject"`` only drains
# replicas the ledger has actually ejected (lenient; more serving capacity
# at the cost of routing to stragglers).  DEGRADED drains under BOTH
# policies: a degraded replica is resharding / running at reduced degree,
# so its spare cycles belong to training catch-up, not inference.
SERVE_DRAIN_STATES: Dict[str, Tuple[HealthState, ...]] = {
    "warn": (
        HealthState.WARN,
        HealthState.EJECTED,
        HealthState.PROBATION,
        HealthState.DEGRADED,
    ),
    "eject": (HealthState.EJECTED, HealthState.DEGRADED),
}

_STATE_NAMES = {
    "ok": HealthState.OK,
    "warn": HealthState.WARN,
    "ejected": HealthState.EJECTED,
    "probation": HealthState.PROBATION,
    "degraded": HealthState.DEGRADED,
}


def serving_eligible(
    state: "HealthState | int | str", drain_on: str = "warn"
) -> bool:
    """True when a replica in ``state`` may serve inference traffic.

    Accepts the native /health JSON state string ("ok"/"warn"/...), the
    IntEnum, or its integer code, so the registry can gate on whichever
    health source it polls.  Unknown states are treated as NOT eligible —
    fail toward draining, never toward routing at a sick replica."""
    if drain_on not in SERVE_DRAIN_STATES:
        raise ValueError(
            f"drain_on must be one of {tuple(SERVE_DRAIN_STATES)}, got {drain_on!r}"
        )
    if isinstance(state, str):
        parsed = _STATE_NAMES.get(state.strip().lower())
        if parsed is None:
            return False
        state = parsed
    try:
        state = HealthState(int(state))
    except (ValueError, TypeError):
        return False
    return state not in SERVE_DRAIN_STATES[drain_on]


def spare_eligible(state: "HealthState | int | str") -> bool:
    """True when a hot spare in ``state`` may be PROMOTED into the quorum
    (redundancy plane, docs/operations.md).

    Promotion is the strictest gate in the repo: swapping a sick spare
    into a quorum trades one dead member for one straggling member, so
    only a clean OK qualifies — WARN/EJECTED/PROBATION spares stay
    shadowing until the ledger clears them. A spare the ledger has never
    seen (it doesn't train, so it may have no samples) reports "ok" and
    qualifies; genuinely unknown state strings do not."""
    if isinstance(state, str):
        parsed = _STATE_NAMES.get(state.strip().lower())
        if parsed is None:
            return False
        state = parsed
    try:
        state = HealthState(int(state))
    except (ValueError, TypeError):
        return False
    return state == HealthState.OK


@dataclass
class _Replica:
    window: List[float] = field(default_factory=list)
    last_step: int = -1
    last_step_s: float = 0.0
    last_wire_s: float = 0.0
    score: float = 0.0
    state: HealthState = HealthState.OK
    strikes: int = 0
    probes_ok: int = 0
    ejections: int = 0
    readmissions: int = 0
    samples_total: int = 0
    ejected_at_ms: float = 0.0
    last_beat_ms: Optional[float] = None
    # degrade plane: last reported group degree (0 = never reported)
    group_world_size: int = 0
    full_group_world_size: int = 0


class HealthLedger:
    """Pure-Python mirror of the native ledger (native/healthwatch.cc).

    Time is an explicit ``now_ms`` argument so tests replay deterministic
    scripts; the native side is driven through the same scripts via
    ``coordination.health_replay`` and must emit the same events.
    """

    def __init__(
        self,
        config: HealthConfig,
        heartbeat_timeout_ms: int = 5000,
        min_replicas: int = 1,
    ) -> None:
        self.config = config
        self.heartbeat_timeout_ms = heartbeat_timeout_ms
        self.min_replicas = min_replicas
        self._replicas: Dict[str, _Replica] = {}
        self._excluded: set = set()

    @property
    def exclusions(self) -> "set[str]":
        return set(self._excluded)

    def on_heartbeat(
        self,
        replica_id: str,
        telemetry: Optional[Mapping[str, Any]],
        now_ms: float,
    ) -> List[Dict[str, Any]]:
        events: List[Dict[str, Any]] = []
        if self.config.mode == "off":
            return events
        rh = self._replicas.setdefault(replica_id, _Replica())
        # Probation demands CONTINUOUS fresh beats: a gap restarts the clock.
        if (
            rh.state is HealthState.EJECTED
            and rh.last_beat_ms is not None
            and now_ms - rh.last_beat_ms > self.heartbeat_timeout_ms
        ):
            rh.ejected_at_ms = now_ms
        rh.last_beat_ms = now_ms

        if (
            telemetry is not None
            and "step" in telemetry
            and rh.state is not HealthState.EJECTED
        ):
            step = int(telemetry["step"])
            if step > rh.last_step:  # dedup: the beat loop re-sends latest
                rh.last_step = step
                step_s = float(telemetry.get("step_s", 0.0))
                wire_s = float(telemetry.get("wire_s", 0.0))
                rh.last_step_s = step_s
                rh.last_wire_s = wire_s
                sample = max(step_s - wire_s, 0.0)
                # Degrade plane: a replica running at reduced group degree
                # self-reports its capacity; its compute sample is scaled to
                # the full-capacity equivalent so it is scored against what
                # it SHOULD cost, never strike-ejected for being
                # legitimately slower.  Beats without both keys take the
                # exact pre-degrade path.
                gws = telemetry.get("group_world_size")
                full = telemetry.get("full_group_world_size")
                if gws is not None and full is not None:
                    gws = int(gws)
                    full = int(full)
                    rh.group_world_size = gws
                    rh.full_group_world_size = full
                    if 0 < gws < full:
                        sample *= gws / float(full)
                        if rh.state in (HealthState.OK, HealthState.WARN):
                            rh.state = HealthState.DEGRADED
                            rh.strikes = 0
                            events.append(
                                {
                                    "kind": "degrade",
                                    "replica_id": replica_id,
                                    "group_world_size": gws,
                                    "full_group_world_size": full,
                                }
                            )
                    elif (
                        rh.state is HealthState.DEGRADED
                        and full > 0
                        and gws >= full
                    ):
                        rh.state = HealthState.OK
                        events.append(
                            {
                                "kind": "restore",
                                "replica_id": replica_id,
                                "group_world_size": gws,
                            }
                        )
                rh.window.append(sample)
                del rh.window[: -self.config.window]
                rh.samples_total += 1
                self._evaluate(replica_id, now_ms, events)
        return events

    def tick(
        self, now_ms: float, prune_after_ms: Optional[int] = None
    ) -> List[Dict[str, Any]]:
        events: List[Dict[str, Any]] = []
        if self.config.mode == "off":
            return events
        prune = (
            prune_after_ms
            if prune_after_ms is not None
            else 10 * self.heartbeat_timeout_ms
        )
        for rid in list(self._replicas):
            rh = self._replicas[rid]
            beat = rh.last_beat_ms if rh.last_beat_ms is not None else -prune
            if now_ms - beat > prune:
                self._excluded.discard(rid)
                del self._replicas[rid]
                continue
            if (
                rh.state is HealthState.EJECTED
                and now_ms - rh.ejected_at_ms >= self.config.probation_ms
                and now_ms - beat < self.heartbeat_timeout_ms
            ):
                rh.state = HealthState.PROBATION
                rh.readmissions += 1
                rh.probes_ok = 0
                self._excluded.discard(rid)
                events.append(
                    {
                        "kind": "readmit",
                        "replica_id": rid,
                        "readmissions": rh.readmissions,
                    }
                )
        return events

    def state_of(self, replica_id: str) -> HealthState:
        rh = self._replicas.get(replica_id)
        return rh.state if rh else HealthState.OK

    def replica(self, replica_id: str) -> Optional[_Replica]:
        return self._replicas.get(replica_id)

    # -- internals --------------------------------------------------------

    def _can_eject(self, now_ms: float) -> bool:
        live = sum(
            1
            for rid, rh in self._replicas.items()
            if rid not in self._excluded
            and rh.last_beat_ms is not None
            and now_ms - rh.last_beat_ms < self.heartbeat_timeout_ms
        )
        return live - 1 >= self.min_replicas

    def _eject(
        self, rid: str, rh: _Replica, now_ms: float, events: List[Dict]
    ) -> None:
        rh.state = HealthState.EJECTED
        rh.ejections += 1
        rh.strikes = 0
        rh.probes_ok = 0
        rh.ejected_at_ms = now_ms
        # last_step is kept: the beat loop re-sends the last pre-ejection
        # (dilated) telemetry until the replica actually steps again
        rh.window = []
        self._excluded.add(rid)
        events.append(
            {
                "kind": "eject",
                "replica_id": rid,
                "score": rh.score,
                "ejections": rh.ejections,
            }
        )

    def _evaluate(
        self, rid: str, now_ms: float, events: List[Dict]
    ) -> None:
        cfg = self.config
        windows = {
            r: rh.window
            for r, rh in self._replicas.items()
            if r not in self._excluded
        }
        scores = straggler_scores(windows, cfg)
        for r, rh in self._replicas.items():
            if r in scores:
                rh.score = scores[r]

        rh = self._replicas[rid]
        s = rh.score

        if rh.state is HealthState.DEGRADED:
            # Capacity-scaled samples keep the peer statistics honest, but
            # a degraded replica never accumulates strikes and never warns:
            # it is slow-but-alive by declaration, and ejecting it would
            # turn a survivable chip loss into a whole-group loss.
            rh.strikes = 0
            return

        if rh.state is HealthState.PROBATION:
            if s > cfg.eject_z:  # one strike in probation: straight back out
                if cfg.mode == "eject" and self._can_eject(now_ms):
                    self._eject(rid, rh, now_ms, events)
                return
            if len(rh.window) < cfg.min_samples:
                return  # unscored warmup samples say nothing about recovery
            rh.probes_ok += 1
            if rh.probes_ok >= cfg.probe_ok:
                rh.state = (
                    HealthState.WARN if s > cfg.warn_z else HealthState.OK
                )
                rh.probes_ok = 0
            return

        rh.strikes = rh.strikes + 1 if s > cfg.eject_z else 0

        if s > cfg.warn_z and rh.state is HealthState.OK:
            rh.state = HealthState.WARN
            events.append(
                {
                    "kind": "straggler_warn",
                    "replica_id": rid,
                    "score": s,
                    "warn_z": cfg.warn_z,
                }
            )
        elif s <= cfg.warn_z and rh.state is HealthState.WARN:
            rh.state = HealthState.OK

        if rh.strikes >= cfg.eject_steps:
            if cfg.mode == "eject" and self._can_eject(now_ms):
                self._eject(rid, rh, now_ms, events)
            else:
                events.append(
                    {
                        "kind": "straggler_warn",
                        "replica_id": rid,
                        "score": s,
                        "would_eject": True,
                        "reason": (
                            "min_replicas floor"
                            if cfg.mode == "eject"
                            else f"mode={cfg.mode}"
                        ),
                    }
                )
                rh.strikes = 0
