"""Thread-backed multiprocessing context.

Role-equivalent of the reference's torchft/multiprocessing_dummy_context.py
(:24-135): exposes the subset of the ``multiprocessing`` context API that
:class:`torchft_tpu.process_group.ProcessGroupBaby` uses (``Process`` and
``Pipe``), but backed by threads and in-process queues. Baby process groups
constructed with this context run their "child" in a thread of the same
process — no spawn/pickling overhead — which keeps the Baby test matrix fast
and debuggable while the spawn context exercises true process isolation.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional, Tuple

__all__ = ["DummyContext", "dummy_context"]


class _DummyConnection:
    """One end of an in-process duplex pipe (Connection API subset)."""

    def __init__(self, rx: "queue.Queue[Any]", tx: "queue.Queue[Any]") -> None:
        self._rx = rx
        self._tx = tx
        self.closed = False

    def send(self, obj: Any) -> None:
        if self.closed:
            raise OSError("handle is closed")
        self._tx.put(obj)

    def recv(self) -> Any:
        item = self._rx.get()
        if item is _CLOSED:
            self.closed = True
            raise EOFError("pipe closed")
        return item

    def poll(self, timeout: Optional[float] = None) -> bool:
        # Connection.poll(None) blocks until data arrives; poll(0) is a probe.
        try:
            if timeout is None:
                item = self._rx.get()
            else:
                item = self._rx.get(block=timeout > 0, timeout=timeout or None)
        except queue.Empty:
            return False
        # Peek semantics: push it back for the recv() that follows.
        self._rx.queue.appendleft(item)  # type: ignore[attr-defined]
        return True

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._tx.put(_CLOSED)


_CLOSED = object()


def _pipe(duplex: bool = True) -> Tuple[_DummyConnection, _DummyConnection]:
    a2b: "queue.Queue[Any]" = queue.Queue()
    b2a: "queue.Queue[Any]" = queue.Queue()
    return _DummyConnection(b2a, a2b), _DummyConnection(a2b, b2a)


class _DummyProcess:
    """threading.Thread dressed up as a multiprocessing.Process."""

    def __init__(
        self,
        target: Callable[..., None],
        args: Tuple[Any, ...] = (),
        daemon: bool = True,
        name: Optional[str] = None,
    ) -> None:
        self._target = target
        self._args = args
        self.daemon = daemon
        self.exitcode: Optional[int] = None
        self._thread = threading.Thread(
            target=self._run, daemon=daemon, name=name or "baby_dummy"
        )
        self.pid: Optional[int] = None

    def _run(self) -> None:
        try:
            self._target(*self._args)
            self.exitcode = 0
        except SystemExit as e:  # child-style exit
            self.exitcode = int(e.code or 0)
        except BaseException:  # noqa: BLE001
            self.exitcode = 1
        finally:
            # EOF parity with real process death: a spawn child's exit closes
            # its Connection fds, which the parent's recv sees as EOFError.
            for a in self._args:
                if isinstance(a, _DummyConnection):
                    a.close()

    def start(self) -> None:
        self._thread.start()
        self.pid = self._thread.ident

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    # Threads cannot be killed — the Baby PG falls back to closing the pipes,
    # which unblocks the worker loop. These exist for API compatibility.
    def terminate(self) -> None:
        pass

    def kill(self) -> None:
        pass


class DummyContext:
    """Thread-backed stand-in for ``multiprocessing.get_context("spawn")``."""

    def Process(self, *args: Any, **kwargs: Any) -> _DummyProcess:
        return _DummyProcess(*args, **kwargs)

    def Pipe(self, duplex: bool = True) -> Tuple[_DummyConnection, _DummyConnection]:
        return _pipe(duplex)


def dummy_context() -> DummyContext:
    return DummyContext()
