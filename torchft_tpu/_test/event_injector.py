"""Fault-injection scheduling for integration tests.

Mirror of the reference EventInjector (manager_integ_test.py:88-166):
events fire at a given (replica, step) — process failure, allreduce future
failure, or a barrier. On top of the reference's process-shaped faults this
injector also schedules NETWORK-shaped ones for the resilient recovery
plane: kill-the-heal-source-mid-transfer at chunk k / corrupt chunk k
(armed on the serving transport via ``HTTPTransport.inject_chunk_fault``)
and delayed/flaky control-plane RPCs (installed process-wide via
``coordination.set_rpc_fault_hook``), so the retry/failover machinery can
be exercised deterministically. ``kill_link`` severs one data-plane ring
link mid-collective (armed via ``ProcessGroupHost.inject_link_fault``) so
the compressed allreduce's in-collective re-route path is what recovers.
``kill_chip`` kills one chip INSIDE a replica group (armed via
``FakeProcessGroupWrapper.inject_group_member_death``) so the degrade-in-
place plane — shrink TP/PP onto the survivors, stay in the quorum — is
what recovers. For the healthwatch plane,
``slow_replica`` dilates the step time a replica REPORTS (installed as a
``Manager.set_telemetry_transform`` hook) so straggler scoring, proactive
ejection, and probationary readmission run without real slowdowns. For the
tracing plane, ``skew_clock`` shifts a replica's wall clock (timestamps
and exported skew estimate together) so the trace merger's skew
correction can be asserted against a known offset.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, Optional, Tuple

from torchft_tpu.process_group import FakeProcessGroupWrapper

__all__ = [
    "EventInjector",
    "InjectedFailure",
    "EventKind",
    "churn_burst",
    "mtbf_script",
]


# ------------------------------------------------------ policy-plane input
# History-style event synthesizers for the adaptive policy plane
# (torchft_tpu/policy.py): deterministic, wall-clock-free event lists in
# the exact shape the lighthouse's recorded-history store emits, so tests
# and benches can drive precise failure-rate signals through
# ``fold_signals`` / ``PolicyEngine.feed`` without killing anything real.
def churn_burst(
    n: int,
    period_s: float,
    replicas: int = 4,
    start_ms: int = 0,
    seq0: int = 0,
) -> list:
    """``n`` depart/rejoin churn cycles, one every ``period_s`` seconds.

    Each cycle is two quorum membership events: replica ``i % replicas``
    missing (one departure = one failure + one churn unit), then the full
    set back half a period later (one join = one churn unit). Folded over
    a window covering all of it this yields ``churn_per_min ==
    2 * n / (span / 60)`` exactly.
    """
    full = [f"replica_{r}" for r in range(replicas)]
    seq = seq0
    events = [
        {
            "ts_ms": start_ms,
            "seq": seq,
            "kind": "quorum",
            "participants": list(full),
        }
    ]
    period_ms = int(period_s * 1000.0)
    for i in range(n):
        t = start_ms + (i + 1) * period_ms
        down = [p for p in full if p != full[i % replicas]]
        seq += 1
        events.append(
            {"ts_ms": t, "seq": seq, "kind": "quorum", "participants": down}
        )
        seq += 1
        events.append(
            {
                "ts_ms": t + period_ms // 2,
                "seq": seq,
                "kind": "quorum",
                "participants": list(full),
            }
        )
    return events


def mtbf_script(
    intervals_s: list,
    replica: str = "replica_0",
    start_ms: int = 0,
    seq0: int = 0,
) -> list:
    """Eject events spaced by the given inter-failure intervals.

    ``mtbf_script([30, 30, 30])`` yields three failures across 90 s of
    event time — folded over a matching window the engine sees ``mtbf_s
    == span / 3``. Use alongside :func:`churn_burst` (offset ``seq0`` /
    ``start_ms`` to interleave) to compose richer fleet narratives.
    """
    events = []
    t = start_ms
    seq = seq0
    for dt in intervals_s:
        t += int(float(dt) * 1000.0)
        seq += 1
        events.append(
            {
                "ts_ms": t,
                "seq": seq,
                "kind": "eject",
                "replica_id": replica,
            }
        )
    return events


class InjectedFailure(Exception):
    """Simulated process crash."""


class EventKind(Enum):
    FAILURE = "failure"
    ALLREDUCE_FAILURE = "allreduce_failure"
    BARRIER = "barrier"
    # network-shaped: arm a serve-side chunk fault on the replica's own
    # checkpoint transport — it fires when a HEALING PEER fetches from it
    HEAL_SOURCE_KILL = "heal_source_kill"
    HEAL_CHUNK_CORRUPT = "heal_chunk_corrupt"
    # network-shaped, data plane: sever one ring link MID-COLLECTIVE so the
    # compressed allreduce's in-collective failover (flood, re-form, finish
    # as a re-routed slow step) is what recovers — not the step-discard path
    KILL_LINK = "kill_link"
    # degrade plane: one chip (group_rank) inside the replica group dies —
    # the replica shrinks TP/PP onto the survivors instead of leaving
    KILL_CHIP = "kill_chip"


@dataclass
class _Event:
    kind: EventKind
    fired: bool = False
    chunk: int = 0
    times: int = 1  # serve count for the heal-source faults; -1 = every serve
    src: int = 0  # kill_link endpoints (group ranks within the quorum)
    dst: int = 0


class EventInjector:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events: Dict[Tuple[int, int], _Event] = {}
        self._barrier: Optional[threading.Barrier] = None
        # stall-prepare gate (prepare/commit configure split tests): the
        # quorum thread blocks inside prepare_configure until the test
        # calls release_prepare(), proving the main thread's jitted step
        # can cross a step boundary while the reconfigure is in flight
        self._prepare_gate: Optional[threading.Event] = None
        self._prepare_stalled = threading.Event()
        self._stall_key: Optional[Tuple[int, int]] = None
        # method -> (remaining fire count, delay_s, error); drained by the
        # process-wide rpc fault hook installed by flake_rpc
        self._rpc_faults: Dict[str, Tuple[int, float, Optional[Exception]]] = {}
        # replica -> step_s dilation factor for the healthwatch telemetry
        # transform (slow_replica); mutable mid-run so a soak can degrade
        # a replica and later let it recover
        self._slow: Dict[int, float] = {}
        # serving-plane faults (kill_snapshot_source / delay_worker_pull):
        # versions whose announcing publisher dies, and the pull delay spec
        self._serve_kill_versions: set = set()
        self._serve_pull_delay: Optional[Tuple[float, int]] = None
        # redundancy-plane faults (corrupt_shard / kill_shard_source):
        # (verdict, owner_prefix, shard_idx|None) -> remaining fire count
        self._shard_faults: Dict[Tuple[str, str, Optional[int]], int] = {}
        self.count = 0

    def stall_prepare_at(self, replica: int, step: int) -> "EventInjector":
        """Arm a one-shot stall: the (replica, step) prepare_configure
        blocks on the quorum thread until ``release_prepare``. Wire it via
        ``FakeProcessGroupWrapper.set_prepare_hook`` with a lambda calling
        ``check_prepare(replica, mgr.current_step())``."""
        with self._lock:
            self._prepare_gate = threading.Event()
            self._prepare_stalled.clear()
            self._stall_key = (replica, step)
        return self

    def wait_prepare_stalled(self, timeout: float = 30.0) -> bool:
        """Block until the armed prepare is actually inside its stall."""
        return self._prepare_stalled.wait(timeout)

    def release_prepare(self) -> None:
        with self._lock:
            gate, self._prepare_gate = self._prepare_gate, None
            self._stall_key = None
        if gate is not None:
            gate.set()

    def check_prepare(self, replica: int, step: int) -> None:
        """Call from a prepare hook; blocks iff the stall is armed for this
        (replica, step). Bounded wait so a test bug cannot hang the quorum
        executor forever."""
        with self._lock:
            if self._stall_key != (replica, step):
                return
            gate = self._prepare_gate
        if gate is not None:
            self._prepare_stalled.set()
            if not gate.wait(timeout=30.0):
                raise RuntimeError(
                    f"stalled prepare replica={replica} step={step} was "
                    "never released"
                )

    def fail_at(self, replica: int, step: int) -> "EventInjector":
        with self._lock:
            self._events[(replica, step)] = _Event(EventKind.FAILURE)
        return self

    def fail_allreduce_at(self, replica: int, step: int) -> "EventInjector":
        with self._lock:
            self._events[(replica, step)] = _Event(EventKind.ALLREDUCE_FAILURE)
        return self

    def barrier_at(self, replica: int, step: int, parties: int) -> "EventInjector":
        with self._lock:
            self._events[(replica, step)] = _Event(EventKind.BARRIER)
            self._barrier = threading.Barrier(parties)
        return self

    def kill_heal_source_at(
        self, replica: int, step: int, chunk: int = 0, times: int = 1
    ) -> "EventInjector":
        """When ``replica`` reaches ``step``, arm its checkpoint transport
        to DROP the connection partway through serving ``chunk`` — the
        healing peer sees a mid-transfer source death and must resume on
        the same source or fail over to a fallback peer. ``times=-1``
        faults every serve (a permanently-dead source: same-source resume
        can never finish, forcing failover)."""
        with self._lock:
            self._events[(replica, step)] = _Event(
                EventKind.HEAL_SOURCE_KILL, chunk=chunk, times=times
            )
        return self

    def corrupt_heal_chunk_at(
        self, replica: int, step: int, chunk: int = 0, times: int = 1
    ) -> "EventInjector":
        """When ``replica`` reaches ``step``, arm its checkpoint transport
        to flip one payload byte of ``chunk`` (crc trailer stays canonical)
        — the healing peer must detect the mismatch and re-fetch."""
        with self._lock:
            self._events[(replica, step)] = _Event(
                EventKind.HEAL_CHUNK_CORRUPT, chunk=chunk, times=times
            )
        return self

    def kill_link(
        self, src: int, dst: int, step: int, at_hop: int = 0
    ) -> "EventInjector":
        """When either endpoint reaches ``step``, arm its host process
        group to sever ring link ``(src, dst)`` from hop ``at_hop`` of the
        next compressed collective. The fault fires *inside* the hop loop:
        the rank that hits it floods a re-route signal, every rank restarts
        under the retry policy, and the ring re-forms around the dead link
        (falling back to an open chain where no ring exists, e.g. world=3)
        — the step commits as a re-routed slow step, surfacing as a
        ``collective_reroute`` count in ``Manager.timings()``.

        ``src``/``dst`` are group ranks within the quorum. The event is
        registered at BOTH endpoints because each rank checks faults
        against its own PG's registry; arming both keeps the discovery
        deterministic regardless of which side's hop runs first. The link
        stays dead for the PG generation (``clear_link_faults`` to heal)."""
        with self._lock:
            ev = dict(src=int(src), dst=int(dst), chunk=int(at_hop))
            self._events[(src, step)] = _Event(EventKind.KILL_LINK, **ev)
            self._events[(dst, step)] = _Event(EventKind.KILL_LINK, **ev)
        return self

    def kill_chip(
        self, replica: int, group_rank: int, at_step: int
    ) -> "EventInjector":
        """When ``replica`` reaches ``at_step``, kill chip ``group_rank``
        INSIDE its replica group (a within-group member death, not a whole-
        replica failure). Fires ``inject_group_member_death`` on the
        replica's wrapped process group, which invokes the manager's
        registered member-death callback — under ``TORCHFT_DEGRADE=on`` the
        replica stages a shrunken TP/PP layout and commits it at the next
        safe point (a re-planned slow step) instead of leaving the quorum."""
        with self._lock:
            self._events[(replica, at_step)] = _Event(
                EventKind.KILL_CHIP, src=int(group_rank)
            )
        return self

    # --------------------------------------------------------- healthwatch
    def slow_replica(self, replica: int, factor: float) -> "EventInjector":
        """Make ``replica`` REPORT ``factor``× its true step time in the
        healthwatch telemetry (the replica does not actually slow down —
        tests stay fast and deterministic). The lighthouse sees a
        straggler and, under ``TORCHFT_HEALTH_MODE=eject``, excludes it
        from the next quorum. Call again with ``factor=1.0`` (or
        :meth:`clear_slow_replica`) to let it 'recover' and exercise
        probationary readmission. Wire via
        ``mgr.set_telemetry_transform(injector.telemetry_transform(r))``."""
        with self._lock:
            self._slow[replica] = float(factor)
        return self

    def clear_slow_replica(self, replica: int) -> None:
        with self._lock:
            self._slow.pop(replica, None)

    def telemetry_transform(self, replica: int):
        """A ``Manager.set_telemetry_transform`` hook bound to ``replica``
        that applies the currently-armed dilation (live: re-arming or
        clearing mid-run changes what the NEXT step reports)."""

        def _transform(telemetry: Dict[str, float]) -> Dict[str, float]:
            with self._lock:
                factor = self._slow.get(replica)
            if factor is not None and "step_s" in telemetry:
                telemetry = dict(telemetry)
                telemetry["step_s"] = telemetry["step_s"] * factor
            return telemetry

        return _transform

    # ------------------------------------------------------------- tracing
    def skew_clock(self, replica_id: str, offset_ms: float) -> "EventInjector":
        """Pretend ``replica_id``'s wall clock runs ``offset_ms`` ahead of
        true time for the tracing plane: its SpanRecorder stamps shifted
        timestamps AND exports a skew estimate shifted by the same amount
        (exactly what a genuinely skewed host looks like to the heartbeat
        estimator), so ``merge_traces`` must correct the ordering back.
        Matched exactly or by prefix (``train_ddp_0`` arms every rank of
        replica 0). Call :meth:`clear_clock_skew` on teardown."""
        from torchft_tpu import tracing

        tracing.set_clock_offset_ms(replica_id, offset_ms)
        return self

    def clear_clock_skew(self) -> None:
        from torchft_tpu import tracing

        tracing.clear_clock_offsets()

    # ------------------------------------------------------- serving plane
    def kill_snapshot_source(self, version: Tuple[int, int]) -> "EventInjector":
        """Kill the serving replica that announces snapshot ``version``
        (``(quorum_id, step)``): the publisher's delta AND full-pull
        endpoints vanish the instant the version exists — the exact window
        where workers are about to pull it.  Downstream, the registry must
        drain the dead source (health/drain) and workers must fail over
        mid-pull.  Installed via the process-wide serving fault hook; call
        :meth:`clear_serve_faults` on teardown."""
        with self._lock:
            self._serve_kill_versions.add((int(version[0]), int(version[1])))
        self._install_serve_hook()
        return self

    def delay_worker_pull(self, delay_s: float, times: int = 1) -> "EventInjector":
        """Make the next ``times`` worker pull cycles (process-wide, any
        worker) sleep ``delay_s`` before polling — the shape of a slow or
        congested pull plane.  Lag gauges grow, the request plane must keep
        answering from the last-applied version.  ``times=-1`` delays every
        pull until cleared."""
        with self._lock:
            self._serve_pull_delay = (float(delay_s), int(times))
        self._install_serve_hook()
        return self

    def clear_serve_faults(self) -> None:
        from torchft_tpu import serving

        with self._lock:
            self._serve_kill_versions.clear()
            self._serve_pull_delay = None
        serving.set_serve_fault_hook(None)

    def _install_serve_hook(self) -> None:
        from torchft_tpu import serving

        serving.set_serve_fault_hook(self._serve_fault_hook)

    def _serve_fault_hook(self, event: str, info: Dict[str, object]):
        if event == "worker_pull":
            with self._lock:
                spec = self._serve_pull_delay
                if spec is None:
                    return None
                delay_s, times = spec
                if times == 0:
                    return None
                if times > 0:
                    self._serve_pull_delay = (delay_s, times - 1)
                self.count += 1
            time.sleep(delay_s)
            return None
        if event in ("announce", "delta_request"):
            version = info.get("version")
            with self._lock:
                armed = (
                    version is not None
                    and tuple(version) in self._serve_kill_versions  # type: ignore[arg-type]
                )
                if armed:
                    self._serve_kill_versions.discard(tuple(version))  # type: ignore[arg-type]
                    self.count += 1
            if armed and event == "announce":
                publisher = info.get("publisher")
                if publisher is not None:
                    publisher.kill()  # type: ignore[union-attr]
                return None
            if armed:
                return "die"
        return None

    # ---------------------------------------------------- redundancy plane
    def corrupt_shard(
        self, replica: str, shard_idx: int, times: int = 1
    ) -> "EventInjector":
        """Flip one byte in shard ``shard_idx`` of owner ``replica``'s
        generation whenever a shard store SERVES it: the fetched body no
        longer matches the announced crc32, so the reconstructing peer
        must detect the mismatch, mark the slot missing, and let parity
        repair it (the codec-level contract, exercised end to end).
        ``replica`` matches exactly or by prefix (``"replica_0"`` arms
        every incarnation ``replica_0:<uuid>``). ``times=-1`` corrupts
        every serve. Installed via the process-wide redundancy fault
        hook; call :meth:`clear_redundancy_faults` on teardown."""
        with self._lock:
            self._shard_faults[("corrupt", str(replica), int(shard_idx))] = (
                int(times)
            )
        self._install_redundancy_hook()
        return self

    def kill_shard_source(
        self,
        replica: str,
        shard_idx: Optional[int] = None,
        times: int = -1,
    ) -> "EventInjector":
        """Drop the connection whenever a store serves owner ``replica``'s
        shard ``shard_idx`` (``None`` = any shard of that owner) — the
        shape of a shard holder dying mid-pull. The reconstructing peer's
        ranged resume budget exhausts against the dead slot and per-shard
        failover marks it missing; decode proceeds from the surviving
        ``k``. ``times=-1`` (default) kills every serve."""
        key = (
            "die",
            str(replica),
            None if shard_idx is None else int(shard_idx),
        )
        with self._lock:
            self._shard_faults[key] = int(times)
        self._install_redundancy_hook()
        return self

    def clear_redundancy_faults(self) -> None:
        from torchft_tpu import redundancy

        with self._lock:
            self._shard_faults.clear()
        redundancy.set_redundancy_fault_hook(None)

    def _install_redundancy_hook(self) -> None:
        from torchft_tpu import redundancy

        redundancy.set_redundancy_fault_hook(self._redundancy_fault_hook)

    def _redundancy_fault_hook(
        self, event: str, info: Dict[str, object]
    ) -> Optional[str]:
        if event != "shard_get":
            return None
        owner = str(info.get("owner", ""))
        idx = int(info.get("idx", -1))  # type: ignore[arg-type]
        with self._lock:
            for key, remaining in self._shard_faults.items():
                verdict, armed_owner, armed_idx = key
                if remaining == 0:
                    continue
                if not (owner == armed_owner or owner.startswith(armed_owner)):
                    continue
                if armed_idx is not None and armed_idx != idx:
                    continue
                if remaining > 0:
                    self._shard_faults[key] = remaining - 1
                self.count += 1
                return verdict
        return None

    # ------------------------------------------------- control-plane flakes
    def flake_rpc(
        self,
        method: str,
        times: int = 1,
        delay_s: float = 0.0,
        error: Optional[Exception] = None,
    ) -> "EventInjector":
        """Make the next ``times`` calls of RPC ``method`` (process-wide,
        any client) sleep ``delay_s`` and then fail with ``error`` (default
        a ``ConnectionError``) — the shape of a lighthouse/manager-server
        blip. Exercises the jittered-backoff retry layer: a flake count
        below the retry budget must degrade to a slower call, not an
        errored one. Call :meth:`clear_rpc_faults` on teardown."""
        from torchft_tpu import coordination

        with self._lock:
            self._rpc_faults[method] = (int(times), float(delay_s), error)
        coordination.set_rpc_fault_hook(self._rpc_fault_hook)
        return self

    def clear_rpc_faults(self) -> None:
        from torchft_tpu import coordination

        with self._lock:
            self._rpc_faults.clear()
        coordination.set_rpc_fault_hook(None)

    def _rpc_fault_hook(self, method: str, addr: str) -> Optional[Exception]:
        with self._lock:
            spec = self._rpc_faults.get(method)
            if spec is None:
                return None
            times, delay_s, error = spec
            if times <= 0:
                return None
            self._rpc_faults[method] = (times - 1, delay_s, error)
            self.count += 1
        if delay_s > 0:
            time.sleep(delay_s)
        return error if error is not None else ConnectionError(
            f"injected rpc flake: {method} -> {addr}"
        )

    def check(
        self,
        replica: int,
        step: int,
        pg: Optional[FakeProcessGroupWrapper] = None,
        transport: Optional[object] = None,
    ) -> None:
        """Call once per (replica, step); fires at most once per event.
        ``transport`` (the replica's own checkpoint transport) is required
        for the heal-source fault kinds."""
        with self._lock:
            event = self._events.get((replica, step))
            if event is None or event.fired:
                return
            event.fired = True
            self.count += 1
            kind = event.kind
            chunk = event.chunk
            times = event.times
            src, dst = event.src, event.dst
        if kind == EventKind.FAILURE:
            raise InjectedFailure(f"injected failure replica={replica} step={step}")
        if kind == EventKind.ALLREDUCE_FAILURE:
            assert pg is not None, "allreduce failure needs the fake PG"
            pg.report_future_error(
                RuntimeError(f"injected allreduce failure replica={replica} step={step}")
            )
        if kind == EventKind.BARRIER:
            assert self._barrier is not None
            self._barrier.wait()
        if kind == EventKind.KILL_LINK:
            assert pg is not None and hasattr(pg, "inject_link_fault"), (
                "kill_link needs a process group with inject_link_fault "
                "(ProcessGroupHost or a wrapper around one)"
            )
            pg.inject_link_fault(src, dst, at_hop=chunk)
        if kind == EventKind.KILL_CHIP:
            assert pg is not None and hasattr(
                pg, "inject_group_member_death"
            ), (
                "kill_chip needs a process group with "
                "inject_group_member_death (FakeProcessGroupWrapper)"
            )
            pg.inject_group_member_death(src)
        if kind in (EventKind.HEAL_SOURCE_KILL, EventKind.HEAL_CHUNK_CORRUPT):
            assert transport is not None and hasattr(
                transport, "inject_chunk_fault"
            ), "heal-source faults need the replica's HTTP checkpoint transport"
            mode = "die" if kind == EventKind.HEAL_SOURCE_KILL else "corrupt"
            transport.inject_chunk_fault(chunk, mode, times=times)
