"""Fault-injection scheduling for integration tests.

Mirror of the reference EventInjector (manager_integ_test.py:88-166):
events fire at a given (replica, step) — process failure, allreduce future
failure, or a barrier.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, Optional, Tuple

from torchft_tpu.process_group import FakeProcessGroupWrapper

__all__ = ["EventInjector", "InjectedFailure", "EventKind"]


class InjectedFailure(Exception):
    """Simulated process crash."""


class EventKind(Enum):
    FAILURE = "failure"
    ALLREDUCE_FAILURE = "allreduce_failure"
    BARRIER = "barrier"


@dataclass
class _Event:
    kind: EventKind
    fired: bool = False


class EventInjector:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events: Dict[Tuple[int, int], _Event] = {}
        self._barrier: Optional[threading.Barrier] = None
        # stall-prepare gate (prepare/commit configure split tests): the
        # quorum thread blocks inside prepare_configure until the test
        # calls release_prepare(), proving the main thread's jitted step
        # can cross a step boundary while the reconfigure is in flight
        self._prepare_gate: Optional[threading.Event] = None
        self._prepare_stalled = threading.Event()
        self._stall_key: Optional[Tuple[int, int]] = None
        self.count = 0

    def stall_prepare_at(self, replica: int, step: int) -> "EventInjector":
        """Arm a one-shot stall: the (replica, step) prepare_configure
        blocks on the quorum thread until ``release_prepare``. Wire it via
        ``FakeProcessGroupWrapper.set_prepare_hook`` with a lambda calling
        ``check_prepare(replica, mgr.current_step())``."""
        with self._lock:
            self._prepare_gate = threading.Event()
            self._prepare_stalled.clear()
            self._stall_key = (replica, step)
        return self

    def wait_prepare_stalled(self, timeout: float = 30.0) -> bool:
        """Block until the armed prepare is actually inside its stall."""
        return self._prepare_stalled.wait(timeout)

    def release_prepare(self) -> None:
        with self._lock:
            gate, self._prepare_gate = self._prepare_gate, None
            self._stall_key = None
        if gate is not None:
            gate.set()

    def check_prepare(self, replica: int, step: int) -> None:
        """Call from a prepare hook; blocks iff the stall is armed for this
        (replica, step). Bounded wait so a test bug cannot hang the quorum
        executor forever."""
        with self._lock:
            if self._stall_key != (replica, step):
                return
            gate = self._prepare_gate
        if gate is not None:
            self._prepare_stalled.set()
            if not gate.wait(timeout=30.0):
                raise RuntimeError(
                    f"stalled prepare replica={replica} step={step} was "
                    "never released"
                )

    def fail_at(self, replica: int, step: int) -> "EventInjector":
        with self._lock:
            self._events[(replica, step)] = _Event(EventKind.FAILURE)
        return self

    def fail_allreduce_at(self, replica: int, step: int) -> "EventInjector":
        with self._lock:
            self._events[(replica, step)] = _Event(EventKind.ALLREDUCE_FAILURE)
        return self

    def barrier_at(self, replica: int, step: int, parties: int) -> "EventInjector":
        with self._lock:
            self._events[(replica, step)] = _Event(EventKind.BARRIER)
            self._barrier = threading.Barrier(parties)
        return self

    def check(
        self, replica: int, step: int, pg: Optional[FakeProcessGroupWrapper] = None
    ) -> None:
        """Call once per (replica, step); fires at most once per event."""
        with self._lock:
            event = self._events.get((replica, step))
            if event is None or event.fired:
                return
            event.fired = True
            self.count += 1
            kind = event.kind
        if kind == EventKind.FAILURE:
            raise InjectedFailure(f"injected failure replica={replica} step={step}")
        if kind == EventKind.ALLREDUCE_FAILURE:
            assert pg is not None, "allreduce failure needs the fake PG"
            pg.report_future_error(
                RuntimeError(f"injected allreduce failure replica={replica} step={step}")
            )
        if kind == EventKind.BARRIER:
            assert self._barrier is not None
            self._barrier.wait()
