"""Massive-fleet control-plane simulation harness.

Drives the *real* lighthouse / aggregator wire protocol with 1000+
lightweight fake replicas on loopback — no JAX, no training step, just the
control plane under fleet-scale load. Each fake replica is a prebuilt
heartbeat frame plus (during the quorum phase) one raw TCP socket holding a
blocked ``quorum`` RPC, so a single host can stand in for a fleet that
would otherwise need a thousand machines.

What it measures per run (one topology x one fleet size):

- **root fan-in bytes/s** during a beats-only steady-state window, read
  from the root's per-method rx counters (``heartbeat`` for a flat fleet,
  ``agg_tick`` for a two-level one);
- **quorum convergence latency**: all live replicas fire a fire-and-forget
  ``quorum`` join (frames written, responses not yet read), then the
  harness selects over all sockets — convergence is first-ok-response
  minus last-join-sent (the quorum is decided and fanning out), and
  ``quorum_delivery_ms`` is last-response-received, which at 1000
  replicas is dominated by draining O(n^2) response bytes through one
  loopback CPU rather than by the control plane itself;
- **/health and /metrics scrape** latency/throughput over HTTP on the same
  port while the fleet keeps beating;
- optional **churn**: kill k replicas (stop their beats), enroll k fresh
  ones, and re-run the quorum round — re-convergence is honest about the
  heartbeat-expiry wait for the dead cohort.

Topologies:

- ``flat``    — every replica beats the root lighthouse directly;
- ``two_level`` — replicas beat pod aggregators (``AggregatorServer``),
  which batch + delta-encode upstream into one ``agg_tick`` per tick.

Sized for a 1-vCPU CI box: beats are sent by a small bounded worker pool
(one cached RPC connection per worker per target, retries disabled), the
health ledger runs in ``off`` mode, and the quorum phase is event-driven
(one selector thread over N sockets) rather than N blocked client threads.
"""

from __future__ import annotations

import json
import math
import selectors
import socket
import struct
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from torchft_tpu.coordination import (
    AggregatorServer,
    LighthouseServer,
    _RawClient,
)
from torchft_tpu.healthwatch import HealthConfig
from torchft_tpu.retry import RetryPolicy

_NO_RETRY = RetryPolicy(max_attempts=1)


def _hostport(addr: str) -> Tuple[str, int]:
    """``http://host:port`` / ``host:port`` -> ``(host, port)``."""
    if "://" in addr:
        addr = addr.split("://", 1)[1]
    host, _, port = addr.rpartition(":")
    host = host.strip("[]") or "127.0.0.1"
    if host in ("0.0.0.0", "::"):
        host = "127.0.0.1"
    return host, int(port)


def _raise_fd_limit(want: int = 65535) -> None:
    """The quorum phase holds one socket per replica (plus the server side
    of each) — lift RLIMIT_NOFILE toward ``want`` so 1000-replica runs
    don't die on EMFILE. Best-effort: capped at the hard limit."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        target = min(want, hard) if hard > 0 else want
        if soft < target:
            resource.setrlimit(resource.RLIMIT_NOFILE, (target, hard))
    except Exception:
        pass


@dataclass
class FleetConfig:
    """Knobs for one simulation run (one topology x one fleet size)."""

    n_replicas: int = 100
    topology: str = "flat"  # "flat" | "two_level"
    n_aggregators: int = 0  # two_level only; 0 -> ceil(n / 64)
    beat_interval_s: float = 1.0
    step_interval_s: float = 10.0  # telemetry step cadence (delta trigger)
    beat_workers: int = 8
    heartbeat_timeout_ms: int = 5000
    quorum_tick_ms: int = 50
    join_timeout_ms: int = 30000
    agg_tick_ms: int = 250
    measure_s: float = 5.0  # beats-only fan-in window
    scrape_iters: int = 25
    churn_replicas: int = 0
    quorum_rpc_timeout_ms: int = 60000
    quorum_rounds: int = 3  # median over rounds (tick-phase noise)
    convergence_timeout_s: float = 120.0
    warmup_timeout_s: float = 60.0


@dataclass
class _FakeReplica:
    rid: str
    target: str  # "host:port" this replica beats / joins through
    step: int = 0
    next_step_t: float = 0.0
    dead: bool = False
    frame: bytes = b""

    def telemetry(self) -> dict:
        # Shaped like the manager's per-step healthwatch payload so frame
        # sizes (and the aggregator's step-delta encoding) are realistic.
        return {
            "host": f"host-{self.rid}",
            "step": self.step,
            "step_time_s": 0.5,
            "wire_time_s": 0.05,
        }

    def rebuild_frame(self) -> None:
        self.frame = json.dumps(
            {"replica_id": self.rid, "telemetry": self.telemetry()},
            sort_keys=True,
            separators=(",", ":"),
        ).encode()

    def maybe_beat(self, client: _RawClient, now: float) -> None:
        if now >= self.next_step_t:
            self.step += 1
            self.next_step_t = now + _STEP_INTERVAL_HOLDER[0]
            self.rebuild_frame()
        client.call_raw("heartbeat", self.frame, timeout=5.0, retry=False)


# maybe_beat is called from worker threads with the config's step interval;
# stash it module-level so _FakeReplica stays a plain dataclass.
_STEP_INTERVAL_HOLDER = [10.0]


class _BeatWorker(threading.Thread):
    """Owns a slice of the fleet; sends each replica's beat once per
    ``beat_interval_s`` round over one cached connection per target."""

    def __init__(self, name: str, replicas: List[_FakeReplica],
                 interval_s: float, stop: threading.Event):
        super().__init__(name=name, daemon=True)
        self.replicas = replicas
        self.interval_s = interval_s
        self.stop_event = stop
        self.beats = 0
        self.errors = 0
        self._clients: Dict[str, _RawClient] = {}

    def _client(self, target: str) -> _RawClient:
        c = self._clients.get(target)
        if c is None:
            c = _RawClient(target, connect_timeout=10.0, retry_policy=_NO_RETRY)
            self._clients[target] = c
        return c

    def run(self) -> None:
        while not self.stop_event.is_set():
            start = time.monotonic()
            for r in list(self.replicas):
                if self.stop_event.is_set():
                    return
                if r.dead:
                    continue
                try:
                    r.maybe_beat(self._client(r.target), time.monotonic())
                    self.beats += 1
                except Exception:
                    self.errors += 1
            elapsed = time.monotonic() - start
            self.stop_event.wait(max(0.0, self.interval_s - elapsed))


class FleetSim:
    """One simulated fleet: a root lighthouse, optional aggregator tier,
    and ``n_replicas`` fake replicas beating through a worker pool."""

    def __init__(self, cfg: FleetConfig):
        if cfg.topology not in ("flat", "two_level"):
            raise ValueError(f"unknown topology: {cfg.topology!r}")
        _raise_fd_limit()
        self.cfg = cfg
        _STEP_INTERVAL_HOLDER[0] = cfg.step_interval_s
        self.root = LighthouseServer(
            bind="127.0.0.1:0",
            min_replicas=cfg.n_replicas,
            join_timeout_ms=cfg.join_timeout_ms,
            quorum_tick_ms=cfg.quorum_tick_ms,
            heartbeat_timeout_ms=cfg.heartbeat_timeout_ms,
            health=HealthConfig(mode="off").to_json(),
            metrics_per_replica_limit=64,
        )
        root_host, root_port = _hostport(self.root.address())
        self.root_target = f"{root_host}:{root_port}"
        self.aggregators: List[AggregatorServer] = []
        targets = [self.root_target]
        if cfg.topology == "two_level":
            n_agg = cfg.n_aggregators or max(1, math.ceil(cfg.n_replicas / 64))
            targets = []
            for i in range(n_agg):
                agg = AggregatorServer(
                    root_addr=self.root_target,
                    bind="127.0.0.1:0",
                    agg_id=f"agg{i:02d}",
                    tick_ms=cfg.agg_tick_ms,
                    heartbeat_timeout_ms=cfg.heartbeat_timeout_ms,
                )
                self.aggregators.append(agg)
                h, p = _hostport(agg.address())
                targets.append(f"{h}:{p}")
        self.replicas: List[_FakeReplica] = [
            _FakeReplica(rid=f"r{i:04d}", target=targets[i % len(targets)])
            for i in range(cfg.n_replicas)
        ]
        self._targets = targets
        self._churn_serial = 0
        self._stop = threading.Event()
        self.workers: List[_BeatWorker] = []
        n_workers = max(1, min(cfg.beat_workers, cfg.n_replicas))
        for w in range(n_workers):
            self.workers.append(_BeatWorker(
                name=f"fleet-beats-{w}",
                replicas=self.replicas[w::n_workers],
                interval_s=cfg.beat_interval_s,
                stop=self._stop,
            ))
        self._status_client = _RawClient(
            self.root_target, connect_timeout=10.0, retry_policy=_NO_RETRY
        )

    # ---------------------------------------------------------------- beats

    def start(self) -> None:
        for w in self.workers:
            w.start()

    def live_replicas(self) -> List[_FakeReplica]:
        return [r for r in self.replicas if not r.dead]

    def root_status(self) -> dict:
        return self._status_client.call("status", {}, timeout=10.0)

    def wait_all_beating(self) -> float:
        """Block until the root has a heartbeat for every live replica
        (through the aggregator tier when two-level); returns how long the
        warmup took."""
        want = {r.rid for r in self.live_replicas()}
        deadline = time.monotonic() + self.cfg.warmup_timeout_s
        t0 = time.monotonic()
        while time.monotonic() < deadline:
            beats = self.root_status().get("heartbeat_ages_ms", {})
            if want.issubset(beats.keys()):
                return time.monotonic() - t0
            time.sleep(0.2)
        missing = sorted(
            want - set(self.root_status().get("heartbeat_ages_ms", {}))
        )
        raise TimeoutError(
            f"warmup: {len(missing)} replicas never reached the root "
            f"(first few: {missing[:5]})"
        )

    # ------------------------------------------------------------- fan-in

    def _rx(self) -> Dict[str, dict]:
        return self.root_status().get("rx", {})

    def measure_fanin(self) -> dict:
        """Beats-only steady-state window: per-method root rx deltas."""
        a = self._rx()
        t0 = time.monotonic()
        time.sleep(self.cfg.measure_s)
        b = self._rx()
        dt = time.monotonic() - t0
        out: Dict[str, float] = {}
        for method in ("heartbeat", "agg_tick"):
            d_bytes = b.get(method, {}).get("bytes", 0) - a.get(method, {}).get("bytes", 0)
            d_calls = b.get(method, {}).get("calls", 0) - a.get(method, {}).get("calls", 0)
            out[f"rx_{method}_bytes_per_s"] = d_bytes / dt
            out[f"rx_{method}_calls_per_s"] = d_calls / dt
        beat_plane = (
            out["rx_heartbeat_bytes_per_s"] + out["rx_agg_tick_bytes_per_s"]
        )
        out["root_fanin_bytes_per_s"] = beat_plane
        # Normalized to one fleet-wide beat interval ("per tick"): what the
        # root ingests for one round of everyone beating once.
        out["root_fanin_bytes_per_tick"] = beat_plane * self.cfg.beat_interval_s
        out["window_s"] = dt
        return out

    # ------------------------------------------------------------- quorum

    def quorum_round(self) -> dict:
        """Fire a fire-and-forget ``quorum`` join from every live replica,
        then select over all sockets until every response frame lands."""
        cfg = self.cfg
        live = self.live_replicas()
        socks: Dict[socket.socket, dict] = {}
        sel = selectors.DefaultSelector()
        send_errors = 0
        try:
            for r in live:
                member = {
                    "replica_id": r.rid,
                    "address": f"fake://{r.rid}",
                    "store_address": f"fake://{r.rid}:0",
                    "step": r.step,
                    "world_size": 1,
                    "shrink_only": False,
                    "commit_failures": 0,
                    "data": "",
                }
                payload = json.dumps(
                    {
                        "method": "quorum",
                        "params": {"requester": member},
                        "timeout_ms": cfg.quorum_rpc_timeout_ms,
                    },
                    sort_keys=True,
                    separators=(",", ":"),
                ).encode()
                try:
                    s = socket.create_connection(
                        _hostport(r.target), timeout=10.0
                    )
                    s.sendall(struct.pack(">I", len(payload)) + payload)
                    s.setblocking(False)
                    socks[s] = {"rid": r.rid, "buf": bytearray(), "ok": None}
                    sel.register(s, selectors.EVENT_READ)
                except OSError:
                    send_errors += 1
            t_sent = time.monotonic()
            pending = len(socks)
            n_ok = 0
            deadline = t_sent + cfg.convergence_timeout_s
            t_first = None
            t_done = None
            while pending > 0 and time.monotonic() < deadline:
                for key, _ in sel.select(timeout=0.25):
                    s = key.fileobj
                    st = socks[s]
                    if st["ok"] is not None:
                        continue
                    try:
                        chunk = s.recv(1 << 18)
                    except BlockingIOError:
                        continue
                    except OSError:
                        chunk = b""
                    if not chunk:
                        st["ok"] = False
                        pending -= 1
                        sel.unregister(s)
                        continue
                    st["buf"] += chunk
                    buf = st["buf"]
                    if len(buf) >= 4:
                        (need,) = struct.unpack(">I", bytes(buf[:4]))
                        if len(buf) >= 4 + need:
                            # Response dump is sorted-keys JSON: an ok
                            # response starts {"ok":true,...} — enough to
                            # classify without parsing 1000 full quorums.
                            st["ok"] = bytes(buf[4:14]).startswith(b'{"ok":true')
                            n_ok += 1 if st["ok"] else 0
                            pending -= 1
                            sel.unregister(s)
                            t_done = time.monotonic()
                            if st["ok"] and t_first is None:
                                t_first = t_done
            converged = pending == 0 and n_ok == len(socks) and len(socks) == len(live)
            if t_done is None:
                t_done = time.monotonic()
            if t_first is None:
                t_first = t_done
            # Convergence = the quorum decision exists and is being fanned
            # out (first ok response after the last join was issued).
            # Delivery = every replica has drained its response; at 1000
            # replicas each response carries the full member list, so the
            # drain is O(n^2) bytes through one loopback CPU — report it
            # separately rather than letting harness serialization masquerade
            # as control-plane latency.
            return {
                "quorum_joined": len(socks),
                "quorum_ok": n_ok,
                "quorum_send_errors": send_errors,
                "quorum_converged": converged,
                "quorum_convergence_ms": (t_first - t_sent) * 1000.0,
                "quorum_delivery_ms": (t_done - t_sent) * 1000.0,
            }
        finally:
            sel.close()
            for s in socks:
                try:
                    s.close()
                except OSError:
                    pass

    # ------------------------------------------------------------- scrape

    def scrape(self) -> dict:
        """Hit GET /health and /metrics on the root while beats continue."""
        host, port = _hostport(self.root_target)
        out: Dict[str, float] = {}
        for path in ("/health", "/metrics"):
            lat: List[float] = []
            size = 0
            t0 = time.monotonic()
            for _ in range(self.cfg.scrape_iters):
                t1 = time.monotonic()
                with urllib.request.urlopen(
                    f"http://{host}:{port}{path}", timeout=10.0
                ) as resp:
                    body = resp.read()
                size = len(body)
                lat.append((time.monotonic() - t1) * 1000.0)
            wall = time.monotonic() - t0
            lat.sort()
            key = path.strip("/")
            out[f"scrape_{key}_p50_ms"] = lat[len(lat) // 2]
            out[f"scrape_{key}_rps"] = self.cfg.scrape_iters / wall
            out[f"scrape_{key}_bytes"] = float(size)
        return out

    # -------------------------------------------------------------- churn

    def churn(self, k: Optional[int] = None) -> dict:
        """Kill ``k`` replicas (their beats stop mid-flight), enroll ``k``
        fresh ones, and run another quorum round. Re-convergence includes
        the heartbeat-expiry wait for the dead cohort — that is the honest
        number an operator would see."""
        k = self.cfg.churn_replicas if k is None else k
        if k <= 0:
            return {}
        live = self.live_replicas()
        victims = live[:: max(1, len(live) // k)][:k]
        for v in victims:
            v.dead = True
        fresh: List[_FakeReplica] = []
        for _ in range(k):
            self._churn_serial += 1
            r = _FakeReplica(
                rid=f"c{self._churn_serial:04d}",
                target=self._targets[self._churn_serial % len(self._targets)],
            )
            fresh.append(r)
            self.replicas.append(r)
        # Hand the fresh cohort to the beat workers round-robin, then give
        # them a beat round to register before they join.
        for i, r in enumerate(fresh):
            self.workers[i % len(self.workers)].replicas.append(r)
        t_kill = time.monotonic()
        self.wait_all_beating()
        round2 = self.quorum_round()
        return {
            "churn_killed": float(len(victims)),
            "churn_added": float(len(fresh)),
            "churn_reconverge_ms": round2["quorum_convergence_ms"],
            "churn_converged": round2["quorum_converged"],
            "churn_total_ms": (time.monotonic() - t_kill) * 1000.0
            + round2["quorum_convergence_ms"],
        }

    # ------------------------------------------------------------ teardown

    def aggregator_stats(self) -> dict:
        if not self.aggregators:
            return {}
        stats = [a.status() for a in self.aggregators]
        return {
            "agg_count": float(len(stats)),
            "agg_ticks_ok": float(sum(s.get("ticks_ok", 0) for s in stats)),
            "agg_ticks_failed": float(
                sum(s.get("ticks_failed", 0) for s in stats)
            ),
            "agg_upstream_bytes": float(
                sum(s.get("upstream_bytes", 0) for s in stats)
            ),
        }

    def beat_stats(self) -> dict:
        return {
            "beats_sent": float(sum(w.beats for w in self.workers)),
            "beat_errors": float(sum(w.errors for w in self.workers)),
        }

    def shutdown(self) -> None:
        self._stop.set()
        for w in self.workers:
            w.join(timeout=10.0)
        for a in self.aggregators:
            a.shutdown()
        self.root.shutdown()


def run_fleet(cfg: FleetConfig) -> dict:
    """Full measurement sequence for one (topology, size) point."""
    sim = FleetSim(cfg)
    try:
        sim.start()
        warmup_s = sim.wait_all_beating()
        metrics: Dict[str, object] = {
            "n_replicas": cfg.n_replicas,
            "topology": cfg.topology,
            "n_aggregators": len(sim.aggregators),
            "beat_interval_s": cfg.beat_interval_s,
            "quorum_tick_ms": cfg.quorum_tick_ms,
            "warmup_s": warmup_s,
        }
        metrics.update(sim.measure_fanin())
        # Convergence is phase-sensitive: the decision lands on the next
        # aggregator tick after the final join, so a single round samples a
        # uniform(0, tick) delay. Median a few rounds for a stable number.
        rounds = [sim.quorum_round() for _ in range(max(1, cfg.quorum_rounds))]
        rounds.sort(key=lambda r: r["quorum_convergence_ms"])
        mid = rounds[len(rounds) // 2]
        metrics.update(mid)
        metrics["quorum_converged"] = all(r["quorum_converged"] for r in rounds)
        metrics["quorum_rounds"] = len(rounds)
        metrics.update(sim.scrape())
        if cfg.churn_replicas > 0:
            metrics.update(sim.churn())
        metrics.update(sim.aggregator_stats())
        metrics.update(sim.beat_stats())
        return metrics
    finally:
        sim.shutdown()
