"""fleetlint: repo-native static invariant analysis for torchft_tpu.

``python -m torchft_tpu.analysis [--ci] [--baseline PATH]`` runs five
AST-based checkers over the whole package:

- **env-contract** — every ``TORCHFT_*`` env read must be registered in
  the central knob registry (``torchft_tpu/knobs.py``), documented in
  ``docs/api.md``, and doctor-covered; registered-but-unread knobs are
  dead.
- **counter-contract** — every key emitted into ``Manager.timings()`` /
  the manager ``/metrics`` exporter must be declared in
  ``analysis/contracts.py`` and documented in ``docs/observability.md``.
- **lock-discipline** — attributes written inside a thread target and
  accessed from other methods must be lock-guarded everywhere or listed
  in the class's ``_atomic_attrs`` allowlist.
- **blocking-calls** — socket/HTTP calls in commit-path modules must ride
  ``retry_call`` or carry an explicit timeout.
- **stale-guard** — handlers consuming ``(epoch, seq)``-stamped messages
  must compare monotonicity before applying state.

Findings are compared against a committed baseline
(``analysis/baseline.json``): pre-existing accepted violations are
explicit, new code is held to zero new findings. The runtime companion,
``analysis/lockgraph.py``, instruments ``threading.Lock``/``RLock`` in
test mode and fails on acquisition-order cycles.

See ``docs/toolchain.md`` ("Static analysis & invariants").
"""

from torchft_tpu.analysis.core import (  # noqa: F401
    Finding,
    load_baseline,
    run_all,
)
from torchft_tpu.analysis import lockgraph  # noqa: F401

CHECKER_NAMES = (
    "env-contract",
    "counter-contract",
    "lock-discipline",
    "blocking-calls",
    "stale-guard",
)
