"""fleetlint core: source loading, constant resolution, findings, baseline.

Everything here is dependency-free stdlib (``ast`` + ``json``) so the
analyzer runs in CI, in the doctor, and as a tier-1 test without touching
JAX or the native plane.
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

PACKAGE_ROOT = Path(__file__).resolve().parents[1]  # torchft_tpu/
REPO_ROOT = PACKAGE_ROOT.parent
DOCS_ROOT = REPO_ROOT / "docs"
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

# directories under torchft_tpu/ that are not production source
_EXCLUDED_PARTS = {"_native", "_test", "analysis", "__pycache__"}


@dataclass(frozen=True)
class Finding:
    """One checker hit. The ``fingerprint`` intentionally excludes the
    line number so unrelated edits don't churn the committed baseline."""

    checker: str  # e.g. "env-contract"
    rule: str  # e.g. "unregistered-read"
    path: str  # repo-relative file
    line: int
    key: str  # stable identity (knob name, Class.attr, call site)
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.checker}:{self.rule}:{self.path}:{self.key}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: [{self.checker}/{self.rule}] "
            f"{self.message}"
        )


@dataclass
class Source:
    """One parsed module."""

    path: Path
    rel: str  # repo-relative path
    text: str
    tree: ast.Module
    # module-level NAME = "literal" string constants
    constants: Dict[str, str] = field(default_factory=dict)
    # from X import NAME bindings (NAME -> X) for cross-module resolution
    imports: Dict[str, str] = field(default_factory=dict)


@dataclass
class Repo:
    """The loaded analysis universe: parsed sources plus doc texts."""

    sources: List[Source]
    docs: Dict[str, str]  # e.g. "api.md" -> text

    # NAME -> set of string values seen across ALL modules (fallback for
    # `from module import SOME_ENV` where the import graph isn't walked)
    global_constants: Dict[str, set] = field(default_factory=dict)

    def by_name(self, filename: str) -> Optional[Source]:
        for s in self.sources:
            if s.path.name == filename:
                return s
        return None

    def resolve_constant(self, src: Source, name: str) -> Optional[str]:
        """Resolve ``name`` to a module-level string constant: local
        module first, then (for imported names) the unique global value."""
        if name in src.constants:
            return src.constants[name]
        values = self.global_constants.get(name)
        if values is not None and len(values) == 1:
            return next(iter(values))
        return None


def _module_constants(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in tree.body:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None or not (
            isinstance(value, ast.Constant) and isinstance(value.value, str)
        ):
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out[t.id] = value.value
    return out


def _module_imports(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                out[alias.asname or alias.name] = node.module
    return out


def load_repo(
    package_root: Optional[Path] = None, docs_root: Optional[Path] = None
) -> Repo:
    package_root = package_root or PACKAGE_ROOT
    docs_root = docs_root or DOCS_ROOT
    sources: List[Source] = []
    for path in sorted(package_root.rglob("*.py")):
        rel_parts = path.relative_to(package_root).parts
        if any(p in _EXCLUDED_PARTS for p in rel_parts):
            continue
        text = path.read_text()
        try:
            tree = ast.parse(text, filename=str(path))
        except SyntaxError:  # stubs/templates never block the run
            continue
        try:
            rel = str(path.relative_to(package_root.parent))
        except ValueError:
            rel = str(path)
        sources.append(
            Source(
                path=path,
                rel=rel,
                text=text,
                tree=tree,
                constants=_module_constants(tree),
                imports=_module_imports(tree),
            )
        )
    repo = Repo(sources=sources, docs={})
    for src in sources:
        for name, value in src.constants.items():
            repo.global_constants.setdefault(name, set()).add(value)
    if docs_root.is_dir():
        for doc in sorted(docs_root.glob("*.md")):
            repo.docs[doc.name] = doc.read_text()
    return repo


# --------------------------------------------------------------- ancestry
def build_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def ancestors(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Iterable[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        yield cur
        cur = parents.get(cur)


def dotted_name(node: ast.expr) -> str:
    """Best-effort dotted rendering of a Name/Attribute chain."""
    parts: List[str] = []
    cur: ast.expr = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif isinstance(cur, ast.Call):
        parts.append(dotted_name(cur.func) + "()")
    return ".".join(reversed(parts))


# --------------------------------------------------------------- baseline
def load_baseline(path: Optional[Path] = None) -> Dict[str, str]:
    """fingerprint -> justification. Missing file = empty baseline."""
    path = path or DEFAULT_BASELINE
    if not Path(path).is_file():
        return {}
    payload = json.loads(Path(path).read_text())
    out: Dict[str, str] = {}
    for entry in payload.get("findings", []):
        out[entry["fingerprint"]] = entry.get("justification", "")
    return out


def save_baseline(
    findings: List[Finding],
    path: Optional[Path] = None,
    justifications: Optional[Dict[str, str]] = None,
) -> Path:
    """Write the given findings as the accepted baseline (``--update``)."""
    path = Path(path or DEFAULT_BASELINE)
    justifications = justifications or {}
    entries = []
    for f in sorted(set(f.fingerprint for f in findings)):
        entries.append(
            {
                "fingerprint": f,
                "justification": justifications.get(
                    f, "accepted pre-existing finding"
                ),
            }
        )
    path.write_text(
        json.dumps({"version": 1, "findings": entries}, indent=2) + "\n"
    )
    return path


def diff_baseline(
    findings: List[Finding], baseline: Dict[str, str]
) -> Tuple[List[Finding], List[str]]:
    """(new findings not in baseline, stale baseline fingerprints)."""
    fps = {f.fingerprint for f in findings}
    new = [f for f in findings if f.fingerprint not in baseline]
    stale = sorted(fp for fp in baseline if fp not in fps)
    return new, stale


# --------------------------------------------------------------- running
def run_all(
    package_root: Optional[Path] = None,
    docs_root: Optional[Path] = None,
    checkers: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Run the selected checkers (default: all five) over the package."""
    from torchft_tpu.analysis import (
        blocking_calls,
        counter_contract,
        env_contract,
        lock_discipline,
        stale_guard,
    )

    repo = load_repo(package_root, docs_root)
    registry = {
        "env-contract": env_contract.check,
        "counter-contract": counter_contract.check,
        "lock-discipline": lock_discipline.check,
        "blocking-calls": blocking_calls.check,
        "stale-guard": stale_guard.check,
    }
    selected = list(checkers) if checkers else list(registry)
    findings: List[Finding] = []
    for name in selected:
        findings.extend(registry[name](repo))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
    return findings
