"""counter-contract checker: every key emitted into ``Manager.timings()``
and the manager-side Prometheus exporter must be declared once (in
``analysis/contracts.py``) and documented in ``docs/observability.md``;
declared keys must still exist in code.

Emission shapes understood (the repo's actual idioms):

- ``self._record_timing("key", …)`` / ``self._bump_counter("key")``
- ``self._on_metric("key", …)`` (the redundancy→Manager metrics bridge)
- dict-literal counter maps whose **values** feed ``_bump_counter`` via a
  variable (``{"heal_retry": "heal_attempts", …}.get(kind)``)
- literal subscript stores ``self._timings["key"] = …`` / ``out["key"]``
- ``for k in ("a", "b"): self._timings[k] = …`` seeding loops
- explicit exporter series: ``reg.gauge_set("torchft_manager_X", …)`` /
  ``counter_set`` / ``observe`` literal first args
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from torchft_tpu.analysis.core import Finding, Repo, Source, dotted_name
from torchft_tpu.analysis.contracts import DECLARED_TIMINGS, DECLARED_SERIES

_EMIT_METHODS = {"_record_timing", "_bump_counter", "_on_metric"}
_SERIES_METHODS = {"gauge_set", "counter_set", "observe"}
_TIMINGS_DICTS = {"_timings", "out"}
# modules whose emissions land in Manager.timings() / manager /metrics
_SCOPED_MODULES = ("manager.py", "redundancy.py")


def _str_arg0(call: ast.Call) -> str | None:
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
        call.args[0].value, str
    ):
        return call.args[0].value
    return None


def extract_emitted(src: Source) -> List[Tuple[str, int]]:
    """(key, line) pairs for every statically visible emission."""
    out: List[Tuple[str, int]] = []
    for fn in [
        n
        for n in ast.walk(src.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]:
        fn_calls_emit_with_var = False
        body_nodes = list(ast.walk(fn))
        for node in body_nodes:
            if not isinstance(node, ast.Call):
                continue
            method = dotted_name(node.func).rsplit(".", 1)[-1]
            if method in _EMIT_METHODS:
                key = _str_arg0(node)
                if key is not None:
                    out.append((key, node.lineno))
                elif node.args:
                    fn_calls_emit_with_var = True
        # a counter map: dict literal string values in a function that
        # also feeds a variable into an emit method
        if fn_calls_emit_with_var:
            for node in body_nodes:
                if isinstance(node, ast.Dict):
                    for v in node.values:
                        if isinstance(v, ast.Constant) and isinstance(
                            v.value, str
                        ):
                            out.append((v.value, v.lineno))
    for node in ast.walk(src.tree):
        # self._timings["k"] = … / out["k"] = …
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Store
        ):
            base = dotted_name(node.value).rsplit(".", 1)[-1]
            if base in _TIMINGS_DICTS:
                key = node.slice
                if isinstance(key, ast.Constant) and isinstance(
                    key.value, str
                ):
                    out.append((key.value, node.lineno))
        # for k in ("a", "b"): self._timings[k] = …
        if isinstance(node, ast.For) and isinstance(
            node.iter, (ast.Tuple, ast.List)
        ):
            elts = node.iter.elts
            if elts and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in elts
            ):
                stores_timings = any(
                    isinstance(n, ast.Subscript)
                    and isinstance(n.ctx, ast.Store)
                    and dotted_name(n.value).rsplit(".", 1)[-1]
                    in _TIMINGS_DICTS
                    for n in ast.walk(node)
                )
                if stores_timings:
                    out.extend((e.value, e.lineno) for e in elts)
    return out


def extract_series(src: Source) -> List[Tuple[str, int]]:
    """Literal Prometheus series names registered on the exporter."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        method = dotted_name(node.func).rsplit(".", 1)[-1]
        if method in _SERIES_METHODS:
            name = _str_arg0(node)
            if name is not None and name.startswith("torchft_manager_"):
                out.append((name, node.lineno))
    return out


def check(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    obs_text = repo.docs.get("observability.md", "")
    emitted: Dict[str, Tuple[Source, int]] = {}
    series: Dict[str, Tuple[Source, int]] = {}
    for src in repo.sources:
        if src.path.name not in _SCOPED_MODULES:
            continue
        for key, line in extract_emitted(src):
            emitted.setdefault(key, (src, line))
        for name, line in extract_series(src):
            series.setdefault(name, (src, line))

    for key, (src, line) in sorted(emitted.items()):
        if key not in DECLARED_TIMINGS:
            findings.append(
                Finding(
                    checker="counter-contract",
                    rule="undeclared-counter",
                    path=src.rel,
                    line=line,
                    key=key,
                    message=(
                        f"timings key {key!r} is emitted here but not "
                        "declared in torchft_tpu/analysis/contracts.py"
                    ),
                )
            )
        elif obs_text and key not in obs_text:
            findings.append(
                Finding(
                    checker="counter-contract",
                    rule="undocumented-counter",
                    path=src.rel,
                    line=line,
                    key=key,
                    message=(
                        f"timings key {key!r} is emitted but never "
                        "mentioned in docs/observability.md"
                    ),
                )
            )
    for name, (src, line) in sorted(series.items()):
        if name not in DECLARED_SERIES:
            findings.append(
                Finding(
                    checker="counter-contract",
                    rule="undeclared-series",
                    path=src.rel,
                    line=line,
                    key=name,
                    message=(
                        f"/metrics series {name!r} is registered here but "
                        "not declared in torchft_tpu/analysis/contracts.py"
                    ),
                )
            )
        elif obs_text and name not in obs_text:
            findings.append(
                Finding(
                    checker="counter-contract",
                    rule="undocumented-series",
                    path=src.rel,
                    line=line,
                    key=name,
                    message=(
                        f"/metrics series {name!r} is not documented in "
                        "docs/observability.md"
                    ),
                )
            )

    # drift in the other direction: declared keys that no longer exist
    # anywhere in the scoped sources (substring scan so keys built by
    # helpers — the pipeline-stats dict, f-strings — stay alive)
    scoped_text = "".join(
        src.text
        for src in repo.sources
        if src.path.name in _SCOPED_MODULES
    )
    contracts_rel = "torchft_tpu/analysis/contracts.py"
    for key in sorted(DECLARED_TIMINGS):
        if f'"{key}"' not in scoped_text and f"'{key}'" not in scoped_text:
            findings.append(
                Finding(
                    checker="counter-contract",
                    rule="dead-declaration",
                    path=contracts_rel,
                    line=1,
                    key=key,
                    message=(
                        f"declared timings key {key!r} no longer appears "
                        "in manager.py/redundancy.py — emission was removed "
                        "without updating the contract"
                    ),
                )
            )
    for name in sorted(DECLARED_SERIES):
        if f'"{name}"' not in scoped_text:
            findings.append(
                Finding(
                    checker="counter-contract",
                    rule="dead-declaration",
                    path=contracts_rel,
                    line=1,
                    key=name,
                    message=(
                        f"declared series {name!r} no longer appears in "
                        "the scoped sources"
                    ),
                )
            )
    return findings
