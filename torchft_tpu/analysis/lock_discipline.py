"""lock-discipline checker: attributes written inside a thread target and
touched from other methods must be lock-guarded everywhere.

Per class, the checker:

1. finds **thread-target methods**: any ``self._x`` passed as ``target=``
   to ``threading.Thread(...)`` (or as a ``threading.Timer`` callback)
   anywhere in the class, then closes transitively over ``self._y(...)``
   calls so helpers reached from the thread body count as thread code;
2. collects ``self.attr`` **writes** inside that closure (assignments,
   aug-assignments, and subscript stores like ``self.counters[k] += 1``),
   ignoring ``__init__`` and attributes that are synchronization
   primitives (``threading.Lock/RLock/Event/Condition`` constructions);
3. collects accesses to the same attributes from methods **outside** the
   closure — that pair is a cross-thread shared attribute;
4. demands every one of those sites sit lexically inside a
   ``with self.<lock>:`` block (any attr assigned from
   ``threading.Lock()``/``RLock()`` in ``__init__``, or named ``*lock*``),
   unless the attribute is listed in the class-level ``_atomic_attrs``
   allowlist (a tuple/set of strings with a justifying comment).

Methods whose name ends in ``_locked`` follow the repo convention "caller
holds the class lock" — their accesses count as guarded (the convention
itself is what code review enforces; this checker enforces everything
else).

One finding per (class, attribute), anchored at the first unguarded site.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from torchft_tpu.analysis.core import Finding, Repo, Source, dotted_name

_SYNC_CONSTRUCTORS = {
    "Lock", "RLock", "Event", "Condition", "Semaphore", "BoundedSemaphore",
    "Barrier", "local",
}
_THREAD_CONSTRUCTORS = {"Thread", "Timer"}


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _base_self_attr(node: ast.AST) -> Optional[str]:
    """``self.x`` or ``self.x[...]`` -> ``x``."""
    attr = _self_attr(node)
    if attr is not None:
        return attr
    if isinstance(node, ast.Subscript):
        return _self_attr(node.value)
    return None


class _ClassInfo:
    def __init__(self, node: ast.ClassDef, src: Source) -> None:
        self.node = node
        self.src = src
        self.methods: Dict[str, ast.AST] = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        self.atomic_attrs = self._atomic_attrs()
        self.lock_attrs = self._lock_attrs()
        self.sync_attrs = self._sync_attrs()
        self.thread_targets = self._thread_target_closure()

    def _atomic_attrs(self) -> Set[str]:
        out: Set[str] = set()
        for item in self.node.body:
            targets: List[ast.expr] = []
            value: Optional[ast.expr] = None
            if isinstance(item, ast.Assign):
                targets, value = item.targets, item.value
            elif isinstance(item, ast.AnnAssign) and item.value is not None:
                targets, value = [item.target], item.value
            if not any(
                isinstance(t, ast.Name) and t.id == "_atomic_attrs"
                for t in targets
            ):
                continue
            if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
                for elt in value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str
                    ):
                        out.add(elt.value)
        return out

    def _attrs_assigned_from(self, ctors: Set[str]) -> Set[str]:
        out: Set[str] = set()
        for method in self.methods.values():
            for node in ast.walk(method):
                if not isinstance(node, ast.Assign):
                    continue
                value = node.value
                if not isinstance(value, ast.Call):
                    continue
                ctor = dotted_name(value.func).rsplit(".", 1)[-1]
                if ctor not in ctors:
                    continue
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        out.add(attr)
        return out

    def _lock_attrs(self) -> Set[str]:
        locks = self._attrs_assigned_from({"Lock", "RLock"})
        # name-based fallback for locks handed in from outside
        for method in self.methods.values():
            for node in ast.walk(method):
                attr = _self_attr(node)
                if attr is not None and "lock" in attr.lower():
                    locks.add(attr)
        return locks

    def _sync_attrs(self) -> Set[str]:
        return self._attrs_assigned_from(_SYNC_CONSTRUCTORS)

    def _thread_target_methods(self) -> Set[str]:
        """Method names passed as Thread targets / Timer callbacks."""
        out: Set[str] = set()
        for method in self.methods.values():
            for node in ast.walk(method):
                if not isinstance(node, ast.Call):
                    continue
                ctor = dotted_name(node.func).rsplit(".", 1)[-1]
                if ctor not in _THREAD_CONSTRUCTORS:
                    continue
                cands: List[ast.expr] = []
                for kw in node.keywords:
                    if kw.arg in ("target", "function"):
                        cands.append(kw.value)
                if ctor == "Timer" and len(node.args) >= 2:
                    cands.append(node.args[1])
                for cand in cands:
                    attr = _self_attr(cand)
                    if attr is not None and attr in self.methods:
                        out.add(attr)
        return out

    def _thread_target_closure(self) -> Set[str]:
        """Thread targets plus every self-method reachable from them."""
        closure = set(self._thread_target_methods())
        frontier = list(closure)
        while frontier:
            name = frontier.pop()
            method = self.methods.get(name)
            if method is None:
                continue
            for node in ast.walk(method):
                if isinstance(node, ast.Call):
                    attr = _self_attr(node.func)
                    if (
                        attr is not None
                        and attr in self.methods
                        and attr not in closure
                    ):
                        closure.add(attr)
                        frontier.append(attr)
        return closure


class _AccessCollector(ast.NodeVisitor):
    """Attribute accesses within one method, tagged guarded/unguarded by
    lexical ``with self.<lock>:`` nesting."""

    def __init__(self, lock_attrs: Set[str]) -> None:
        self.lock_attrs = lock_attrs
        self.guard_depth = 0
        # attr -> list of (line, guarded, is_write)
        self.accesses: Dict[str, List[Tuple[int, bool, bool]]] = {}

    def _record(self, attr: str, line: int, is_write: bool) -> None:
        self.accesses.setdefault(attr, []).append(
            (line, self.guard_depth > 0, is_write)
        )

    def _is_lock_item(self, item: ast.withitem) -> bool:
        expr = item.context_expr
        # with self._lock: …  (or a Call like self._rw.read_lock())
        attr = _base_self_attr(expr)
        if attr is None and isinstance(expr, ast.Call):
            attr = _base_self_attr(expr.func)
        return attr is not None and attr in self.lock_attrs

    def visit_With(self, node: ast.With) -> None:
        guarded = any(self._is_lock_item(item) for item in node.items)
        for item in node.items:
            self.visit(item)
        if guarded:
            self.guard_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if guarded:
            self.guard_depth -= 1

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None:
            self._record(attr, node.lineno, isinstance(node.ctx, ast.Store))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = _base_self_attr(node.target)
        if attr is not None:
            self._record(attr, node.lineno, True)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        attr = _self_attr(node.value)
        if attr is not None and isinstance(node.ctx, ast.Store):
            self._record(attr, node.lineno, True)
        self.generic_visit(node)


def _collect(
    info: _ClassInfo, method: ast.AST
) -> Dict[str, List[Tuple[int, bool, bool]]]:
    c = _AccessCollector(info.lock_attrs)
    for stmt in getattr(method, "body", []):
        c.visit(stmt)
    return c.accesses


def check(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for src in repo.sources:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            info = _ClassInfo(node, src)
            if not info.thread_targets:
                continue
            thread_acc: Dict[str, List[Tuple[int, bool, bool]]] = {}
            other_acc: Dict[str, List[Tuple[int, bool, bool]]] = {}
            for name, method in info.methods.items():
                if name == "__init__":
                    continue  # construction happens-before the thread start
                acc = _collect(info, method)
                bucket = (
                    thread_acc if name in info.thread_targets else other_acc
                )
                locked_by_convention = name.endswith("_locked")
                for attr, sites in acc.items():
                    if locked_by_convention:
                        sites = [(ln, True, w) for ln, _, w in sites]
                    bucket.setdefault(attr, []).extend(sites)
            skip = (
                info.atomic_attrs
                | info.lock_attrs
                | info.sync_attrs
                | info.thread_targets
                | set(info.methods)
            )
            for attr, t_sites in sorted(thread_acc.items()):
                if attr in skip or not any(w for _, _, w in t_sites):
                    continue  # only attrs WRITTEN from thread code
                o_sites = other_acc.get(attr)
                if not o_sites:
                    continue  # not shared outside the thread closure
                unguarded = [
                    (line, w)
                    for line, guarded, w in t_sites + o_sites
                    if not guarded
                ]
                if not unguarded:
                    continue
                line = min(line for line, _ in unguarded)
                findings.append(
                    Finding(
                        checker="lock-discipline",
                        rule="unguarded-shared-attr",
                        path=src.rel,
                        line=line,
                        key=f"{node.name}.{attr}",
                        message=(
                            f"{node.name}.{attr} is written inside a "
                            "thread target and accessed from other "
                            f"methods, but {len(unguarded)} site(s) are "
                            "outside any lock — guard them with the "
                            "class lock or add the attr to _atomic_attrs "
                            "with a justification"
                        ),
                    )
                )
    return findings
