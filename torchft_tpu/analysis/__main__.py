"""fleetlint CLI: ``python -m torchft_tpu.analysis [--ci] [--baseline P]``.

Modes:

- default: print every finding (including baselined ones, marked) and a
  summary; exit 0 unless there are findings absent from the baseline.
- ``--ci``: same gate, terse output — meant for the workflow step and
  pre-commit hooks. Stale baseline entries (accepted findings that no
  longer fire) are warnings in both modes so the baseline shrinks over
  time instead of fossilizing.
- ``--update``: rewrite the baseline to the current findings, keeping
  existing justifications for fingerprints that survive.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

from torchft_tpu.analysis import CHECKER_NAMES
from torchft_tpu.analysis.core import (
    DEFAULT_BASELINE,
    diff_baseline,
    load_baseline,
    run_all,
    save_baseline,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torchft_tpu.analysis",
        description="fleetlint: repo-native invariant analyzer",
    )
    parser.add_argument(
        "--ci",
        action="store_true",
        help="terse output; exit nonzero on findings beyond the baseline",
    )
    parser.add_argument(
        "--baseline",
        type=Path,
        default=DEFAULT_BASELINE,
        help=f"baseline file (default: {DEFAULT_BASELINE})",
    )
    parser.add_argument(
        "--update",
        action="store_true",
        help="rewrite the baseline to the current findings",
    )
    parser.add_argument(
        "--checker",
        action="append",
        choices=CHECKER_NAMES,
        help="run only the named checker (repeatable; default: all)",
    )
    args = parser.parse_args(argv)

    t0 = time.monotonic()
    findings = run_all(checkers=args.checker)
    elapsed = time.monotonic() - t0
    baseline = load_baseline(args.baseline)
    new, stale = diff_baseline(findings, baseline)

    if args.update:
        kept = {
            fp: why
            for fp, why in baseline.items()
            if fp in {f.fingerprint for f in findings}
        }
        path = save_baseline(findings, args.baseline, justifications=kept)
        print(
            f"fleetlint: baseline rewritten with {len(findings)} "
            f"finding(s) -> {path}"
        )
        return 0

    if not args.ci:
        for f in findings:
            mark = "" if f.fingerprint not in baseline else " [baselined]"
            print(f.render() + mark)
    else:
        for f in new:
            print(f.render())
    for fp in stale:
        print(
            f"fleetlint: WARNING stale baseline entry (no longer fires): "
            f"{fp}"
        )
    print(
        f"fleetlint: {len(findings)} finding(s), {len(new)} new, "
        f"{len(baseline)} baselined ({len(stale)} stale) "
        f"[{len(args.checker or CHECKER_NAMES)} checkers, "
        f"{elapsed:.2f}s]"
    )
    if new:
        print(
            "fleetlint: FAIL — fix the findings above or (for accepted "
            "pre-existing debt) add them to the baseline with a "
            "justification via --update",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
