"""env-contract checker: every ``TORCHFT_*`` env read must be registered,
documented, and doctor-covered; every registered knob must be alive.

Read shapes understood (the repo's actual idioms):

- ``os.environ.get(K)`` / ``os.environ[K]`` / ``os.getenv(K)``
- ``knobs.env_raw(K)`` and the typed ``knobs.env_*`` wrappers
- one level of helper indirection: a local function whose parameter feeds
  any of the above (``_pick(env, ...)`` / ``_get(name, ...)``) has its
  call sites resolved instead, so the `from_env` pattern every config
  class uses resolves to real knob names.

``K`` itself may be a string literal, a module-level ``*_ENV`` constant,
or a constant imported from another module (resolved via the repo-wide
constant table when unambiguous).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from torchft_tpu import knobs
from torchft_tpu.analysis.core import Finding, Repo, Source, dotted_name

_KNOB_WRAPPERS = {"env_raw", "env_str", "env_int", "env_float", "env_bool"}


def _env_key_expr(node: ast.AST) -> Optional[ast.expr]:
    """If ``node`` is an env-read expression, return the key expression."""
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        last = name.rsplit(".", 1)[-1]
        if name.endswith("environ.get") or last == "getenv":
            return node.args[0] if node.args else None
        if last in _KNOB_WRAPPERS and (
            "knobs" in name or name in _KNOB_WRAPPERS
        ):
            return node.args[0] if node.args else None
        return None
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        if dotted_name(node.value).endswith("environ"):
            key = node.slice
            return key if isinstance(key, ast.expr) else None
    return None


class _FunctionIndex(ast.NodeVisitor):
    """Map every env-read key expression to its enclosing function def."""

    def __init__(self) -> None:
        self.func_stack: List[ast.AST] = []
        self.reads: List[Tuple[ast.expr, Optional[ast.AST], int]] = []
        self.calls_by_name: Dict[str, List[ast.Call]] = {}

    def _visit_func(self, node: ast.AST) -> None:
        self.func_stack.append(node)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Name):
            self.calls_by_name.setdefault(node.func.id, []).append(node)
        key = _env_key_expr(node)
        if key is not None:
            self.reads.append(
                (key, self.func_stack[-1] if self.func_stack else None,
                 node.lineno)
            )
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        key = _env_key_expr(node)
        if key is not None:
            self.reads.append(
                (key, self.func_stack[-1] if self.func_stack else None,
                 node.lineno)
            )
        self.generic_visit(node)


def _param_names(fn: ast.AST) -> List[str]:
    args = getattr(fn, "args", None)
    if args is None:
        return []
    return [a.arg for a in args.posonlyargs + args.args]


def _resolve_key(
    repo: Repo, src: Source, key: ast.expr
) -> Optional[str]:
    if isinstance(key, ast.Constant) and isinstance(key.value, str):
        return key.value
    if isinstance(key, ast.Name):
        return repo.resolve_constant(src, key.id)
    return None


def collect_env_reads(repo: Repo) -> List[Tuple[Source, int, str]]:
    """All resolved TORCHFT_* env reads as (source, line, knob name)."""
    out: List[Tuple[Source, int, str]] = []
    for src in repo.sources:
        if src.path.name == "knobs.py":
            continue  # the registry implementation, not a consumer
        idx = _FunctionIndex()
        idx.visit(src.tree)
        for key, fn, line in idx.reads:
            resolved = _resolve_key(repo, src, key)
            if resolved is not None:
                if resolved.startswith("TORCHFT_"):
                    out.append((src, line, resolved))
                continue
            # helper indirection: the key is a parameter of the enclosing
            # function — resolve that function's call sites instead
            if not (isinstance(key, ast.Name) and fn is not None):
                continue
            params = _param_names(fn)
            if key.id not in params:
                continue
            pos = params.index(key.id)
            fn_name = getattr(fn, "name", "")
            for call in idx.calls_by_name.get(fn_name, []):
                arg: Optional[ast.expr] = None
                if len(call.args) > pos:
                    arg = call.args[pos]
                else:
                    for kw in call.keywords:
                        if kw.arg == key.id:
                            arg = kw.value
                if arg is None:
                    continue
                resolved = _resolve_key(repo, src, arg)
                if resolved is not None and resolved.startswith("TORCHFT_"):
                    out.append((src, call.lineno, resolved))
    return out


def _doctor_check_names(repo: Repo) -> Set[str]:
    doctor = repo.by_name("doctor.py")
    if doctor is None:
        return set()
    names: Set[str] = set()
    for node in doctor.tree.body:
        targets = node.targets if isinstance(node, ast.Assign) else (
            [node.target] if isinstance(node, ast.AnnAssign) else []
        )
        if not any(
            isinstance(t, ast.Name) and t.id == "CHECKS" for t in targets
        ):
            continue
        value = node.value
        if value is None or not isinstance(value, (ast.List, ast.Tuple)):
            continue
        for elt in value.elts:
            if (
                isinstance(elt, ast.Tuple)
                and elt.elts
                and isinstance(elt.elts[0], ast.Constant)
                and isinstance(elt.elts[0].value, str)
            ):
                names.add(elt.elts[0].value)
    return names


def check(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    reads = collect_env_reads(repo)
    read_names = {name for _, _, name in reads}
    registry = knobs.all_knobs()
    doctor_checks = _doctor_check_names(repo)
    api_text = repo.docs.get("api.md", "")

    # 1) reads of unregistered knobs
    seen: Set[Tuple[str, str]] = set()
    for src, line, name in reads:
        if name in registry or (src.rel, name) in seen:
            continue
        seen.add((src.rel, name))
        findings.append(
            Finding(
                checker="env-contract",
                rule="unregistered-read",
                path=src.rel,
                line=line,
                key=name,
                message=(
                    f"{name} is read here but not registered in "
                    "torchft_tpu/knobs.py — declare it (type, default, doc "
                    "anchor, doctor coverage)"
                ),
            )
        )

    knobs_rel = "torchft_tpu/knobs.py"
    for name, knob in sorted(registry.items()):
        # 2) registered but never read anywhere: dead knob
        if name not in read_names:
            findings.append(
                Finding(
                    checker="env-contract",
                    rule="dead-knob",
                    path=knobs_rel,
                    line=1,
                    key=name,
                    message=(
                        f"{name} is registered but never read in the "
                        "package — remove it or wire it up"
                    ),
                )
            )
        # 3) registered but absent from the docs/api.md knob index
        if api_text and name not in api_text:
            findings.append(
                Finding(
                    checker="env-contract",
                    rule="undocumented-knob",
                    path=knobs_rel,
                    line=1,
                    key=name,
                    message=(
                        f"{name} is not mentioned in docs/api.md — add it "
                        "to the environment-contract table"
                    ),
                )
            )
        # 3b) the doc anchor must point at a doc file that mentions it
        doc_file = knob.doc.split("#", 1)[0]
        doc_text = repo.docs.get(doc_file)
        if doc_text is not None and name not in doc_text:
            findings.append(
                Finding(
                    checker="env-contract",
                    rule="doc-anchor-drift",
                    path=knobs_rel,
                    line=1,
                    key=name,
                    message=(
                        f"{name} declares doc anchor {knob.doc!r} but "
                        f"docs/{doc_file} never mentions it"
                    ),
                )
            )
        # 4) doctor coverage
        if knob.doctor is None:
            findings.append(
                Finding(
                    checker="env-contract",
                    rule="undoctored-knob",
                    path=knobs_rel,
                    line=1,
                    key=name,
                    message=(
                        f"{name} has no doctor check validating it — add "
                        "coverage or baseline with a justification"
                    ),
                )
            )
        elif doctor_checks and knob.doctor not in doctor_checks:
            findings.append(
                Finding(
                    checker="env-contract",
                    rule="doctor-check-missing",
                    path=knobs_rel,
                    line=1,
                    key=name,
                    message=(
                        f"{name} claims doctor coverage by "
                        f"{knob.doctor!r}, but doctor.py has no such check"
                    ),
                )
            )
    return findings
