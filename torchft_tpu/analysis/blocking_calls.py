"""blocking-call-in-hot-path checker: network calls in the hot-path
modules must either ride :func:`torchft_tpu.retry.retry_call` or carry an
explicit ``timeout=``.

Scope is the modules whose threads sit on the training/serving hot path:
``manager.py``, ``serving.py``, ``redundancy.py``, ``coordination.py``.
A bare ``urlopen(url)`` there blocks its thread for the kernel default
(minutes) when a peer wedges — exactly the failure mode the paper's
fault-tolerance plane exists to bound.

Blocking shapes recognized:

- ``urllib.request.urlopen(...)`` (and bare ``urlopen``)
- ``socket.create_connection(...)``
- ``http.client.HTTPConnection(...)`` / ``HTTPSConnection(...)``
- ``requests.<verb>(...)``

A call is exempt when it has a ``timeout=`` keyword, or when it sits
lexically inside a ``retry_call(...)`` expression (whose policy owns the
deadline), or inside a function whose name ends with ``_with_timeout``.
"""

from __future__ import annotations

import ast
from typing import List, Set

from torchft_tpu.analysis.core import Finding, Repo, dotted_name

_SCOPED_MODULES = ("manager.py", "serving.py", "redundancy.py",
                   "coordination.py")
_BLOCKING_NAMES = {
    "urlopen", "create_connection", "HTTPConnection", "HTTPSConnection",
}
_RETRY_WRAPPERS = {"retry_call", "retry_call_async"}


def _is_blocking(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    last = name.rsplit(".", 1)[-1]
    if last in _BLOCKING_NAMES:
        return True
    if name.startswith("requests."):
        return True
    return False


def _has_timeout(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords)


def check(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for src in repo.sources:
        if src.path.name not in _SCOPED_MODULES:
            continue
        # every node lexically inside a retry_call(...) expression is
        # exempt — the retry policy owns the deadline
        exempt: Set[int] = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                last = dotted_name(node.func).rsplit(".", 1)[-1]
                if last in _RETRY_WRAPPERS:
                    for sub in ast.walk(node):
                        exempt.add(id(sub))
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name.endswith("_with_timeout"):
                    for sub in ast.walk(node):
                        exempt.add(id(sub))
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not _is_blocking(node):
                continue
            if id(node) in exempt or _has_timeout(node):
                continue
            callee = dotted_name(node.func) or "<call>"
            findings.append(
                Finding(
                    checker="blocking-calls",
                    rule="missing-timeout",
                    path=src.rel,
                    line=node.lineno,
                    key=f"{callee}@L{node.lineno}",
                    message=(
                        f"{callee}(...) on the hot path has no timeout= "
                        "and is not wrapped in retry_call — a wedged peer "
                        "blocks this thread for the kernel default"
                    ),
                )
            )
    return findings
