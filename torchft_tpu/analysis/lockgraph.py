"""Runtime lock-order race detector (test mode).

:func:`watch` monkeypatches ``threading.Lock`` / ``threading.RLock`` so
every lock created inside the block is instrumented: each acquisition
records a directed edge from every lock the acquiring thread already
holds to the one being acquired, keyed by the locks' **creation sites**
(``file:line`` of the ``Lock()`` call). A cycle in that graph is a
lock-order inversion — two threads that interleave the other way
deadlock — reported by :meth:`LockGraph.cycles` without needing the
unlucky schedule to actually happen. Hold times are tracked per site so
tests can also flag a lock pinned across a slow call on the hot path.

Wired into the serving/redundancy integration tests and the chaos soak
(zero-cycle assertions); enable ad hoc with ``TORCHFT_LOCKGRAPH=1``-style
test harnesses via::

    with lockgraph.watch() as graph:
        ...  # exercise the planes
    lockgraph.assert_clean(graph)

Locks created *before* ``watch()`` ran are untouched — instrumentation is
opt-in per block, never a production overhead.

Granularity caveat: the graph is keyed by creation site, so two locks
born at the same ``file:line`` (a lock-per-shard list comprehension, two
``Lock()`` calls on one line) collapse into one node and nesting them is
NOT reported — the same class-granularity tradeoff kernel lockdep makes,
which keeps consistently-ordered per-instance lock arrays from flagging
as false positives. Give each distinctly-ordered lock its own line.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Set, Tuple


def _creation_site(depth: int = 1) -> str:
    import sys

    frame = sys._getframe(depth)
    # walk out of this module so the site names the caller's code
    while frame is not None and frame.f_code.co_filename == __file__:
        frame = frame.f_back
    if frame is None:
        return "<unknown>"
    fname = frame.f_code.co_filename
    for marker in ("torchft_tpu", "tests"):
        idx = fname.find(marker)
        if idx != -1:
            fname = fname[idx:]
            break
    return f"{fname}:{frame.f_lineno}"


class LockGraph:
    """Global acquisition-order graph over instrumented locks."""

    def __init__(self, hold_warn_ms: float = 200.0) -> None:
        self.hold_warn_ms = hold_warn_ms
        self._mu = threading.Lock()  # a REAL lock, never instrumented
        # edge: held-site -> acquired-site, with one example thread name
        self._edges: Dict[Tuple[str, str], str] = {}
        self._max_hold_ms: Dict[str, float] = defaultdict(float)
        self._tls = threading.local()
        self._n_locks = 0
        self._n_acquires = 0

    # ---------------------------------------------------- bookkeeping
    def _held_stack(self) -> List[Tuple[object, str, float]]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def on_created(self) -> None:
        with self._mu:
            self._n_locks += 1

    def on_acquired(self, lock: object, site: str) -> None:
        stack = self._held_stack()
        held_sites = []
        for held_lock, held_site, _ in stack:
            if held_lock is lock:  # reentrant RLock: no self-edge
                continue
            held_sites.append(held_site)
        if held_sites:
            thread = threading.current_thread().name
            with self._mu:
                for held_site in held_sites:
                    if held_site != site:
                        self._edges.setdefault((held_site, site), thread)
        with self._mu:
            self._n_acquires += 1
        stack.append((lock, site, time.perf_counter()))

    def on_released(self, lock: object, site: str) -> None:
        stack = self._held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is lock:
                _, _, t0 = stack.pop(i)
                hold_ms = (time.perf_counter() - t0) * 1000.0
                with self._mu:
                    if hold_ms > self._max_hold_ms[site]:
                        self._max_hold_ms[site] = hold_ms
                return

    # -------------------------------------------------------- queries
    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._mu:
            return dict(self._edges)

    def cycles(self) -> List[List[str]]:
        """Cycles in the site-level acquisition-order graph (each as the
        ordered list of sites; a two-element cycle is the classic
        A→B / B→A inversion)."""
        adj: Dict[str, Set[str]] = defaultdict(set)
        for (a, b) in self.edges():
            adj[a].add(b)
        cycles: List[List[str]] = []
        seen_cycles: Set[Tuple[str, ...]] = set()
        WHITE, GRAY, BLACK = 0, 1, 2
        color: Dict[str, int] = defaultdict(int)
        path: List[str] = []

        def dfs(node: str) -> None:
            color[node] = GRAY
            path.append(node)
            for nxt in sorted(adj.get(node, ())):
                if color[nxt] == GRAY:
                    cycle = path[path.index(nxt):]
                    canon = tuple(sorted(cycle))
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        cycles.append(list(cycle))
                elif color[nxt] == WHITE:
                    dfs(nxt)
            path.pop()
            color[node] = BLACK

        for node in sorted(adj):
            if color[node] == WHITE:
                dfs(node)
        return cycles

    def hold_violations(
        self, threshold_ms: Optional[float] = None
    ) -> Dict[str, float]:
        limit = self.hold_warn_ms if threshold_ms is None else threshold_ms
        with self._mu:
            return {
                site: ms
                for site, ms in self._max_hold_ms.items()
                if ms > limit
            }

    def report(self) -> Dict[str, object]:
        with self._mu:
            max_holds = dict(self._max_hold_ms)
            n_locks, n_acq, n_edges = (
                self._n_locks, self._n_acquires, len(self._edges)
            )
        return {
            "locks": n_locks,
            "acquires": n_acq,
            "edges": n_edges,
            "cycles": self.cycles(),
            "max_hold_ms": max_holds,
        }


class _InstrumentedLock:
    """Wraps a real Lock/RLock; reports acquire/release to the graph and
    speaks enough of the protocol (including the private Condition hooks)
    to be substitutable anywhere the stdlib types are."""

    def __init__(self, inner: object, graph: LockGraph, site: str) -> None:
        self._inner = inner
        self._graph = graph
        self._site = site
        graph.on_created()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._graph.on_acquired(self, self._site)
        return got

    def release(self) -> None:
        self._graph.on_released(self, self._site)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: object) -> None:
        self.release()

    # threading.Condition private protocol (waits release the lock
    # without calling release(), so bookkeeping must follow)
    def _release_save(self) -> object:
        self._graph.on_released(self, self._site)
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return inner._release_save()
        inner.release()
        return None

    def _acquire_restore(self, state: object) -> None:
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        self._graph.on_acquired(self, self._site)

    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __repr__(self) -> str:
        return f"<lockgraph wrapper {self._site} of {self._inner!r}>"


_install_mu = threading.Lock()


@contextmanager
def watch(hold_warn_ms: float = 200.0) -> Iterator[LockGraph]:
    """Instrument every ``threading.Lock``/``RLock`` created inside the
    block and yield the shared :class:`LockGraph`. Nested/concurrent
    watches are refused (the patch is process-global)."""
    graph = LockGraph(hold_warn_ms=hold_warn_ms)
    real_lock = threading.Lock
    real_rlock = threading.RLock
    if not _install_mu.acquire(blocking=False):
        raise RuntimeError("lockgraph.watch() is already active")

    def make_lock() -> _InstrumentedLock:
        return _InstrumentedLock(real_lock(), graph, _creation_site())

    def make_rlock() -> _InstrumentedLock:
        return _InstrumentedLock(real_rlock(), graph, _creation_site())

    threading.Lock = make_lock  # type: ignore[misc]
    threading.RLock = make_rlock  # type: ignore[misc]
    try:
        yield graph
    finally:
        threading.Lock = real_lock  # type: ignore[misc]
        threading.RLock = real_rlock  # type: ignore[misc]
        _install_mu.release()


def assert_clean(
    graph: LockGraph, max_hold_ms: Optional[float] = None
) -> None:
    """Fail on any acquisition-order cycle; optionally also on hot-path
    hold times above ``max_hold_ms`` (left off by default so loaded CI
    hosts don't flake integration tests on wall-clock)."""
    cycles = graph.cycles()
    assert not cycles, (
        f"lock-order cycles detected (A→B / B→A inversions): {cycles}; "
        f"edges={sorted(graph.edges())}"
    )
    if max_hold_ms is not None:
        slow = graph.hold_violations(max_hold_ms)
        assert not slow, (
            f"locks held >{max_hold_ms}ms on the hot path: {slow}"
        )
