"""stale-guard checker: handlers that consume ``(epoch, seq)``-versioned
messages must compare them for monotonicity before acting.

In this stack every cross-replica message that mutates state carries an
``(epoch, seq)`` pair (lighthouse leases, shard-directory announces,
snapshot manifests). A handler that extracts both fields but never
compares them will happily apply a delayed duplicate from a previous
epoch — the classic zombie-writer bug the paper's reconfiguration
protocol exists to prevent.

Detection: a function whose body *loads* both an ``"epoch"`` and a
``"seq"`` field (via ``msg["epoch"]`` / ``msg.get("epoch")`` /
``payload.epoch`` attribute access, or parameters named ``epoch``/``seq``)
must also contain at least one ordering comparison (``<``, ``>``, ``<=``,
``>=``, ``!=``) whose operands mention an epoch/seq-derived name, or a
tuple compare of both. Functions named like constructors/serializers
(``__init__``, ``to_*``, ``encode*``, ``snapshot*``) are skipped — they
produce versions rather than consume them.
"""

from __future__ import annotations

import ast
from typing import List, Set

from torchft_tpu.analysis.core import Finding, Repo, dotted_name

_FIELDS = ("epoch", "seq")
_ORDERING_OPS = (ast.Lt, ast.Gt, ast.LtE, ast.GtE, ast.NotEq)
_PRODUCER_PREFIXES = ("to_", "encode", "snapshot", "make_", "build_")


def _field_of(node: ast.AST) -> str | None:
    """Which versioned field (if any) this expression loads."""
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        key = node.slice
        if isinstance(key, ast.Constant) and key.value in _FIELDS:
            return key.value
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name.endswith(".get") and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and arg.value in _FIELDS:
                return arg.value
    if isinstance(node, ast.Attribute) and node.attr in _FIELDS:
        return node.attr
    return None


def _versioned_names(fn: ast.AST) -> Set[str]:
    """Names bound from epoch/seq field loads (``e = msg["epoch"]``),
    plus parameters literally named epoch/seq."""
    names: Set[str] = set(_FIELDS)
    args = getattr(fn, "args", None)
    if args is not None:
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            if a.arg in _FIELDS:
                names.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _field_of(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        if isinstance(node, (ast.Tuple,)) and isinstance(
            getattr(node, "ctx", None), ast.Store
        ):
            pass  # tuple unpack handled below via parent Assign
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Tuple
        ):
            for t in node.targets:
                if isinstance(t, ast.Tuple) and len(t.elts) == len(
                    node.value.elts
                ):
                    for tgt, val in zip(t.elts, node.value.elts):
                        if isinstance(tgt, ast.Name) and _field_of(val):
                            names.add(tgt.id)
    return names


def _mentions_version(node: ast.AST, names: Set[str]) -> bool:
    for sub in ast.walk(node):
        if _field_of(sub):
            return True
        if isinstance(sub, ast.Name) and sub.id in names:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in names:
            return True
    return False


def _has_guard(fn: ast.AST, names: Set[str]) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        if not any(isinstance(op, _ORDERING_OPS) for op in node.ops):
            continue
        operands = [node.left, *node.comparators]
        if any(_mentions_version(o, names) for o in operands):
            return True
    return False


def check(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for src in repo.sources:
        for node in ast.walk(src.tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if node.name == "__init__" or node.name.startswith(
                _PRODUCER_PREFIXES
            ):
                continue
            loaded = set()
            for sub in ast.walk(node):
                f = _field_of(sub)
                if f:
                    loaded.add(f)
            if loaded != {"epoch", "seq"}:
                continue  # consumes at most one field: not a versioned msg
            names = _versioned_names(node)
            if _has_guard(node, names):
                continue
            findings.append(
                Finding(
                    checker="stale-guard",
                    rule="missing-stale-guard",
                    path=src.rel,
                    line=node.lineno,
                    key=node.name,
                    message=(
                        f"{node.name}() consumes both epoch and seq but "
                        "never compares them for monotonicity — a delayed "
                        "duplicate from an old epoch will be applied"
                    ),
                )
            )
    return findings
