"""Declared observability contract: the single list of every key that is
allowed to flow through ``Manager.timings()`` and the manager-side
Prometheus exporter.

The counter-contract checker
(``torchft_tpu/analysis/counter_contract.py``) statically extracts the
keys ``manager.py`` / ``redundancy.py`` actually emit and diffs both
directions: an emitted key missing here is *undeclared* (new telemetry
must land with a declaration and a docs/observability.md row), and a key
declared here that no longer appears in code is a *dead declaration*
(emission was removed without updating the contract). Every declared key
must also be mentioned in ``docs/observability.md``.
"""

from __future__ import annotations

from typing import Dict

# key -> one-line meaning (kept short: docs/observability.md is the
# operator-facing reference; this is the machine-checked index)
DECLARED_TIMINGS: Dict[str, str] = {
    # quorum / reconfigure phases
    "quorum_overlap_s": "control-plane time on the quorum thread",
    "configure_prepare_s": "overlappable half of the PG reconfigure",
    "configure_commit_s": "serializing half of the PG reconfigure",
    "should_commit_rpc_s": "commit-vote RPC wall clock",
    "bookkeeping_s": "residual commit-path bookkeeping",
    # heal plane
    "heal_send_s": "serving a live checkpoint to a peer",
    "heal_recv_s": "fetching + applying a live checkpoint",
    "heal_chunks": "chunks in the last heal stream",
    "heal_mb_per_s": "last heal stream throughput",
    "heal_attempts": "cumulative heal tries (incl. same-source retries)",
    "heal_failovers": "cumulative mid-heal source switches",
    "chunk_crc_failures": "chunks refetched after integrity mismatch",
    # allreduce pipeline
    "allreduce_s": "submission→resolve wall clock of the last collective",
    "allreduce_pack_s": "summed per-bucket pack stage",
    "allreduce_wire_s": "summed per-bucket wire stage",
    "allreduce_unpack_s": "summed per-bucket unpack stage",
    "allreduce_buckets": "buckets in the last streamed allreduce",
    "overlap_efficiency": "fraction of wire time hidden behind other stages",
    "collective_reroute": "cumulative mid-collective link reroutes",
    # control plane (two-level)
    "via_aggregator": "1 when control RPCs ride the pod aggregator",
    "aggregator_failovers": "cumulative aggregator→root failovers",
    "rpc_retries": "cumulative retried control-plane RPCs",
    # health plane
    "health_state": "lighthouse health state code for this replica",
    "straggler_score": "quorum-relative modified z-score",
    "ejections": "cumulative proactive ejections of this replica",
    "readmissions": "cumulative probationary readmissions",
    # policy plane (adaptive FT control, quorum-safe-point application)
    "policy_seq": "latest policy frame sequence seen at a safe point",
    "policy_applies": "frames whose overrides were enforced live",
    "policy_intents": "frames recorded in observe mode (no knob touched)",
    # degrade plane (in-place TP/PP shrink after an intra-group chip loss)
    "degraded_reshard_s": "last in-place k→k-1 reshard wall clock",
    "degrade_events": "cumulative in-place degrades of this replica",
    "restored_events": "cumulative full-degree restores after a degrade",
    # observability honesty counters
    "dropped_events": "telemetry events shed by the bounded drain",
    "trace_dropped": "spans overwritten in the trace ring",
    # serving plane (commit-path publisher)
    "serve_publish_s": "commit-path snapshot handoff wall clock",
    "serve_published_total": "snapshots handed to the publisher",
    "serve_publish_errors_total": "failed snapshot handoffs",
    # redundancy plane — manager side
    "shard_stage_hot_s": "hot-path cost of handing state to the stager",
    "standby_skipped": "standby snapshots refused while mid-heal",
    "reconstructs": "heals satisfied by parallel shard reconstruct",
    "reconstruct_failures": "reconstruct attempts that fell back to pull",
    "reconstruct_s": "last parallel reconstruct wall clock",
    "reconstruct_mb_per_s": "last parallel reconstruct throughput",
    "shard_corrupt": "shards that failed crc32 on the GET path",
    "shard_fetch_failed": "shard GETs that failed outright",
    "spare_promote_step": "step at which this spare was promoted",
    # redundancy plane — stager/spare bridge (_on_metric)
    "shard_stage_s": "staging wall clock off the hot path",
    "shard_stage_snapshot_s": "hot-path state snapshot cost",
    "shard_encode_s": "GF(256) parity encode wall clock",
    "shard_stage_bytes": "bytes in the last staged state blob",
    "shards_staged": "cumulative shards PUT to peer stores",
    "shard_stage_skipped": "stagings skipped by the interval knob",
    "shard_stage_dropped": "stagings dropped by newest-wins queueing",
    "shard_stage_failed": "stagings that failed end to end",
    "shard_put_failed": "individual shard PUTs that failed",
    "shard_announce_rejected": "directory announces rejected as stale",
    "spare_prefetch_s": "hot-spare decode-ahead wall clock",
    "spare_prefetch_steps": "generations prefetched by the hot spare",
}

# explicit Prometheus series registered on the manager exporter (beyond
# the mechanical torchft_manager_<timings-key> projections)
DECLARED_SERIES: Dict[str, str] = {
    "torchft_manager_step": "current manager step",
    "torchft_manager_quorum_id": "current PG generation",
    "torchft_manager_trace_spans_total": "spans recorded into the ring",
    "torchft_manager_clock_skew_ms": "heartbeat-derived skew estimate",
    "torchft_manager_clock_skew_rtt_ms": "RTT of the best skew sample",
}
