"""Fleet tracing plane: per-manager span recorder, skew-corrected
Chrome-trace merge, and the recorded-history fold.

The repo's telemetry was per-replica (Manager.timings(), flight-recorder
breadcrumbs, /health) — useful for one process, useless for "which replica
stalled bucket 7 of step 412" across a fleet. This module closes that gap:

- :class:`SpanRecorder` — a bounded ring buffer of structured spans the
  Manager records around its control-plane and wire phases (quorum /
  prepare / commit, per-bucket pack / wire / unpack, heal chunks, RPC
  retries, reroutes). Every span carries ``(quorum_id, step)`` and the
  recorder's ``replica_id``, so spans from different replicas of the same
  step correlate without a global clock. Recording is an O(1) dict append
  behind one lock — cheap enough to stay on by default (the
  ``bench.py --tracing`` gate holds the <1% line).
- **Skew correction** — each export stamps the replica's clock-skew
  estimate vs the lighthouse (``ManagerServer.clock_skew()``: the beat
  loop's RPC round-trip midpoint minus the response ``server_ms`` —
  replica-minus-lighthouse, positive when this clock runs ahead; best
  = minimum-RTT sample). :func:`merge_traces` shifts every replica onto
  the lighthouse's clock, so cross-replica ordering is correct within the
  estimated-skew bound (~RTT/2 on a quiet network).
- :func:`merge_traces` / ``python -m torchft_tpu.trace merge`` — N span
  dumps in, one Chrome-trace JSON out (load in Perfetto or
  chrome://tracing): one process row per replica, one thread row per span
  category.
- :func:`history_fold` — the canonical Python fold over the lighthouse's
  recorded-history JSONL (quorum transitions / heals / health events /
  telemetry snapshots). The native read path ``tft_history_replay``
  (coordination.history_replay) computes the SAME summary; parity is
  pinned by test, same convention as the healthwatch replay hooks. This
  is the replay substrate the ROADMAP's adaptive policy engine consumes.

Env knobs (read once per Manager via :meth:`TraceConfig.from_env`):

- ``TORCHFT_TRACE``: ``1``/``0`` — master switch (default on).
- ``TORCHFT_TRACE_BUFFER``: ring capacity in spans (default 4096).
- ``TORCHFT_TRACE_SAMPLE``: fraction of steps traced, deterministic by
  step hash so all replicas keep/drop the SAME steps (default 1.0).
- ``TORCHFT_TRACE_DIR``: auto-dump directory; empty falls back next to
  the flight-recorder dump path (``TORCHFT_FR_BASE_PATH``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Deque, Dict, Iterable, List, Optional

TRACE_ENV = "TORCHFT_TRACE"
TRACE_BUFFER_ENV = "TORCHFT_TRACE_BUFFER"
TRACE_SAMPLE_ENV = "TORCHFT_TRACE_SAMPLE"
TRACE_DIR_ENV = "TORCHFT_TRACE_DIR"

_DEFAULT_BUFFER = 4096

__all__ = [
    "TraceConfig",
    "SpanRecorder",
    "merge_traces",
    "history_fold",
    "parse_history",
    "set_clock_offset_ms",
    "clear_clock_offsets",
]


# --------------------------------------------------------------- test hooks
# Injected per-replica clock offsets (event_injector.skew_clock): shifts the
# recorder's own clock, which self-consistently shifts its estimated skew vs
# the lighthouse by the same amount — exactly what a genuinely skewed host
# looks like, so the merge-corrects-ordering test exercises the real path.
_clock_offsets: Dict[str, float] = {}
_clock_offsets_lock = threading.Lock()


def set_clock_offset_ms(replica_id: str, offset_ms: float) -> None:
    """TEST ONLY: pretend ``replica_id``'s wall clock runs ``offset_ms``
    ahead of true time (matched exactly or by prefix, like
    ``slow_replica``)."""
    with _clock_offsets_lock:
        _clock_offsets[replica_id] = float(offset_ms)


def clear_clock_offsets() -> None:
    with _clock_offsets_lock:
        _clock_offsets.clear()


def _offset_ms_for(replica_id: str) -> float:
    with _clock_offsets_lock:
        if not _clock_offsets:
            return 0.0
        if replica_id in _clock_offsets:
            return _clock_offsets[replica_id]
        for key, off in _clock_offsets.items():
            if replica_id.startswith(key):
                return off
    return 0.0


# ------------------------------------------------------------------- config
@dataclass
class TraceConfig:
    enabled: bool = True
    buffer: int = _DEFAULT_BUFFER
    sample: float = 1.0
    dump_dir: str = ""

    @classmethod
    def from_env(cls) -> "TraceConfig":
        cfg = cls()
        cfg.enabled = os.environ.get(TRACE_ENV, "1").strip() not in (
            "0", "off", "false", "no",
        )
        try:
            cfg.buffer = max(16, int(os.environ.get(TRACE_BUFFER_ENV, "")))
        except ValueError:
            cfg.buffer = _DEFAULT_BUFFER
        try:
            cfg.sample = min(
                1.0, max(0.0, float(os.environ.get(TRACE_SAMPLE_ENV, "")))
            )
        except ValueError:
            cfg.sample = 1.0
        cfg.dump_dir = os.environ.get(TRACE_DIR_ENV, "")
        return cfg


def step_sampled(step: int, sample: float) -> bool:
    """Deterministic per-step sampling decision, identical on every
    replica (Knuth multiplicative hash — no RNG, no cross-replica skew in
    WHICH steps are kept, so sampled steps still merge into full fleet
    timelines)."""
    if sample >= 1.0:
        return True
    if sample <= 0.0:
        return False
    return ((step * 2654435761) % (1 << 32)) / float(1 << 32) < sample


# ----------------------------------------------------------------- recorder
class _SpanHandle:
    """Context manager for an in-progress span; records on exit."""

    __slots__ = ("_rec", "name", "cat", "args", "_t0_us", "_t0_pc")

    def __init__(self, rec: "SpanRecorder", name: str, cat: str, args: dict):
        self._rec = rec
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self) -> "_SpanHandle":
        self._t0_us = self._rec._now_us()
        self._t0_pc = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        dur_us = int((time.perf_counter() - self._t0_pc) * 1e6)
        self._rec._append(
            self.name, self.cat, self._t0_us, max(dur_us, 1), self.args
        )


class SpanRecorder:
    """Bounded ring of structured spans for ONE replica.

    Thread-safe; every mutator is a no-op when disabled, so Manager call
    sites never branch. Timestamps are epoch microseconds from the local
    wall clock (plus any injected test offset); the skew estimate stamped
    into :meth:`export` is what lets the merger move them onto the
    lighthouse's clock.
    """

    def __init__(
        self,
        replica_id: str,
        config: Optional[TraceConfig] = None,
    ) -> None:
        self._replica_id = replica_id
        self._config = config if config is not None else TraceConfig.from_env()
        self._spans: Deque[Dict[str, Any]] = deque(maxlen=self._config.buffer)
        self._lock = threading.Lock()
        self._quorum_id: Optional[int] = None
        self._step: Optional[int] = None
        self._step_on = True  # sampling decision for the current step
        self._skew_ms = 0.0
        self._rtt_ms = 0.0
        self._skew_samples = 0
        self._dropped = 0
        self._recorded = 0

    @property
    def enabled(self) -> bool:
        return self._config.enabled

    @property
    def replica_id(self) -> str:
        return self._replica_id

    # ------------------------------------------------------------- context
    def set_context(
        self,
        quorum_id: Optional[int] = None,
        step: Optional[int] = None,
    ) -> None:
        """Update the ``(quorum_id, step)`` stamped into subsequent spans;
        re-evaluates the per-step sampling decision on a step change."""
        with self._lock:
            if quorum_id is not None:
                self._quorum_id = quorum_id
            if step is not None and step != self._step:
                self._step = step
                self._step_on = step_sampled(step, self._config.sample)

    def set_skew(
        self, skew_ms: float, rtt_ms: float = 0.0, samples: int = 0
    ) -> None:
        """Feed the latest heartbeat-derived skew estimate
        (``ManagerServer.clock_skew()``). An injected test clock offset
        shifts the estimate too — a host whose clock runs fast is fast in
        both its span stamps and its measured skew."""
        with self._lock:
            self._skew_ms = float(skew_ms)
            self._rtt_ms = float(rtt_ms)
            self._skew_samples = int(samples)

    # ----------------------------------------------------------- recording
    def _now_us(self) -> int:
        off = _offset_ms_for(self._replica_id)
        return time.time_ns() // 1000 + int(off * 1000)

    def _append(
        self, name: str, cat: str, ts_us: int, dur_us: int, args: dict
    ) -> None:
        if not self._config.enabled:
            return
        with self._lock:
            if not self._step_on:
                return
            if len(self._spans) == self._spans.maxlen:
                self._dropped += 1
            self._recorded += 1
            span: Dict[str, Any] = {
                "name": name,
                "cat": cat,
                "ts_us": ts_us,
                "dur_us": dur_us,
                "quorum_id": self._quorum_id,
                "step": self._step,
            }
            if args:
                span["args"] = args
            self._spans.append(span)

    def span(self, name: str, cat: str = "step", **args: Any) -> _SpanHandle:
        """``with tracer.span("quorum", cat="quorum"): ...``"""
        return _SpanHandle(self, name, cat, args)

    def record(
        self,
        name: str,
        cat: str,
        t0_us: int,
        t1_us: int,
        **args: Any,
    ) -> None:
        """Record a completed interval given absolute epoch-us endpoints."""
        self._append(name, cat, int(t0_us), max(int(t1_us - t0_us), 1), args)

    def record_rel(
        self,
        name: str,
        cat: str,
        t0_pc: float,
        t1_pc: float,
        **args: Any,
    ) -> None:
        """Record a completed interval given ``time.perf_counter()``
        endpoints (the pipeline marks' native form): anchored to the wall
        clock at call time, so recently-finished intervals land within
        scheduler noise of their true wall positions."""
        anchor_us = self._now_us()
        anchor_pc = time.perf_counter()
        t0_us = anchor_us + int((t0_pc - anchor_pc) * 1e6)
        t1_us = anchor_us + int((t1_pc - anchor_pc) * 1e6)
        self._append(name, cat, t0_us, max(t1_us - t0_us, 1), args)

    def instant(self, name: str, cat: str, **args: Any) -> None:
        """Zero-duration marker (RPC retry, reroute, heal chunk events)."""
        self._append(name, cat, self._now_us(), 1, args)

    # ------------------------------------------------------------- exports
    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "spans": float(len(self._spans)),
                "recorded": float(self._recorded),
                "dropped": float(self._dropped),
            }

    def export(self) -> Dict[str, Any]:
        """One replica's span dump: merge-ready, skew-stamped."""
        with self._lock:
            return {
                "replica_id": self._replica_id,
                "clock": "epoch_us",
                "skew_ms": self._skew_ms + _offset_ms_for(self._replica_id),
                "rtt_ms": self._rtt_ms,
                "skew_samples": self._skew_samples,
                "dropped": self._dropped,
                "spans": list(self._spans),
            }

    def dump(self, path: "str | Path | None" = None) -> Optional[Path]:
        """Write :meth:`export` as JSON; never raises (dumps run on
        failure paths). Default location: ``TORCHFT_TRACE_DIR``, else next
        to the flight-recorder base path, else None (disabled)."""
        try:
            if path is None:
                base = self._config.dump_dir or os.environ.get(
                    "TORCHFT_FR_BASE_PATH", ""
                )
                if not base:
                    return None
                d = Path(base) if self._config.dump_dir else Path(
                    str(base) + "_traces"
                )
                d.mkdir(parents=True, exist_ok=True)
                path = d / f"trace_{self._replica_id}_{time.time_ns()}.json"
            path = Path(path)
            path.parent.mkdir(parents=True, exist_ok=True)
            with open(path, "w") as f:
                json.dump(self.export(), f)
            return path
        except Exception:  # noqa: BLE001 — observability must not raise
            return None


# -------------------------------------------------------------------- merge
def merge_traces(dumps: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge N replicas' span dumps into one Chrome-trace JSON dict.

    Each replica becomes a trace process (pid ordered by replica_id) and
    each span category a thread within it; every timestamp is shifted by
    ``-skew_ms`` onto the lighthouse's clock, so the same step's spans
    from different replicas line up within the skew-estimate error.
    Load the result in Perfetto / chrome://tracing.
    """
    events: List[Dict[str, Any]] = []
    ordered = sorted(dumps, key=lambda d: str(d.get("replica_id", "")))
    for pid, dump in enumerate(ordered):
        rid = str(dump.get("replica_id", f"replica_{pid}"))
        skew_us = float(dump.get("skew_ms", 0.0)) * 1000.0
        events.append({
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {
                "name": f"{rid} (skew {dump.get('skew_ms', 0.0):+.3f}ms)"
            },
        })
        tids: Dict[str, int] = {}
        for span in dump.get("spans", []):
            cat = str(span.get("cat", "step"))
            tid = tids.setdefault(cat, len(tids))
            args = dict(span.get("args", {}))
            args["quorum_id"] = span.get("quorum_id")
            args["step"] = span.get("step")
            args["replica_id"] = rid
            events.append({
                "name": str(span.get("name", "?")),
                "cat": cat,
                "ph": "X",
                "ts": float(span.get("ts_us", 0)) - skew_us,
                "dur": max(float(span.get("dur_us", 1)), 1.0),
                "pid": pid,
                "tid": tid,
                "args": args,
            })
        for cat, tid in tids.items():
            events.append({
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": cat},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ------------------------------------------------------------------ history
def parse_history(text: str) -> List[Dict[str, Any]]:
    """Parse recorded-history JSONL content into an event list (blank
    lines skipped) — the Python twin of the native read path's parser."""
    events: List[Dict[str, Any]] = []
    for line in text.splitlines():
        if not line.strip():
            continue
        events.append(json.loads(line))
    return events


def load_history(source: str) -> List[Dict[str, Any]]:
    """THE history loader: accepts a path to a ``--history`` JSONL file
    (plain or gzip'd, sniffed by magic bytes — fleets routinely gzip
    rotated histories) or raw JSONL content, and returns the event list.

    Every consumer funnels through here — the ``trace history`` CLI, the
    policy replay CLI, and ``coordination.history_replay`` (which keeps
    its content-only signature but shares this parser) — so path
    vs. content can never diverge again between entry points.
    """
    import gzip
    import os

    if "\n" not in source and os.path.exists(source):
        with open(source, "rb") as f:
            blob = f.read()
        if blob[:2] == b"\x1f\x8b":
            blob = gzip.decompress(blob)
        return parse_history(blob.decode("utf-8"))
    return parse_history(source)


def history_fold(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Canonical fold over history events -> summary.

    MUST stay field-for-field identical to ``history_fold`` in
    native/history.cc (the ``tft_history_replay`` summary); the parity
    test drives the same JSONL through both.
    """
    kinds: Dict[str, int] = {}
    replicas = set()
    count = 0
    last_quorum_id = -1
    max_step = -1
    first_ts = -1
    last_ts = -1
    for e in events:
        count += 1
        kind = str(e.get("kind", "unknown"))
        kinds[kind] = kinds.get(kind, 0) + 1
        if "replica_id" in e:
            replicas.add(str(e["replica_id"]))
        for rid in e.get("participants", []):
            replicas.add(str(rid))
        if "quorum_id" in e:
            last_quorum_id = int(e["quorum_id"])
        if "step" in e:
            max_step = max(max_step, int(e["step"]))
        if "to_step" in e:
            max_step = max(max_step, int(e["to_step"]))
        if "ts_ms" in e:
            ts = int(e["ts_ms"])
            if first_ts < 0:
                first_ts = ts
            last_ts = ts
    return {
        "count": count,
        "kinds": kinds,
        "replicas": sorted(replicas),
        "quorum_transitions": kinds.get("quorum", 0),
        "last_quorum_id": last_quorum_id,
        "heals": kinds.get("heal", 0),
        "ejections": kinds.get("eject", 0),
        "readmissions": kinds.get("readmit", 0),
        "warns": kinds.get("straggler_warn", 0),
        "max_step": max_step,
        "first_ts_ms": first_ts,
        "last_ts_ms": last_ts,
    }
