"""torchft_tpu: TPU-native per-step fault tolerance for data-parallel training.

A from-scratch JAX/XLA framework with the capabilities of torchft
(reference: /root/reference): lighthouse quorum control plane (C++),
reconfigurable collective communicators, error-swallowing managed allreduce,
two-phase commit, live peer-to-peer checkpoint recovery, and fault-tolerant
DDP / HSDP / LocalSGD / DiLoCo training algorithms.
"""

__version__ = "0.1.0"
