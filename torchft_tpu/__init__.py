"""torchft_tpu: TPU-native per-step fault tolerance for data-parallel training.

A from-scratch JAX/XLA framework with the capabilities of torchft
(reference: /root/reference): lighthouse quorum control plane (C++),
reconfigurable collective communicators, error-swallowing managed allreduce,
two-phase commit, live peer-to-peer checkpoint recovery, and fault-tolerant
DDP / HSDP / LocalSGD / DiLoCo training algorithms.
"""

__version__ = "0.1.0"

# Lazy top-level exports (reference: torchft/__init__.py re-exports the user
# API). Lazy so that `import torchft_tpu` stays light — no jax/native loads
# until a symbol is touched.
_EXPORTS = {
    "Manager": "torchft_tpu.manager",
    "WorldSizeMode": "torchft_tpu.manager",
    "ProcessGroupHost": "torchft_tpu.process_group",
    "ProcessGroupBabyHost": "torchft_tpu.process_group",
    "ProcessGroupDummy": "torchft_tpu.process_group",
    "ManagedProcessGroup": "torchft_tpu.process_group",
    "ProcessGroupXLA": "torchft_tpu.process_group_xla",
    "DistributedDataParallel": "torchft_tpu.ddp",
    "PureDistributedDataParallel": "torchft_tpu.ddp",
    "BucketPlan": "torchft_tpu.bucketing",
    "BufferPool": "torchft_tpu.bucketing",
    "OptimizerWrapper": "torchft_tpu.optim",
    "LocalSGD": "torchft_tpu.local_sgd",
    "DiLoCo": "torchft_tpu.local_sgd",
    "DistributedSampler": "torchft_tpu.data",
    "StatefulDataIterator": "torchft_tpu.data",
    "HTTPTransport": "torchft_tpu.checkpointing",
    "PGTransport": "torchft_tpu.checkpointing",
    "DurableCheckpointer": "torchft_tpu.checkpointing",
    "LighthouseServer": "torchft_tpu.coordination",
    "LighthouseClient": "torchft_tpu.coordination",
    "ManagerServer": "torchft_tpu.coordination",
    "ManagerClient": "torchft_tpu.coordination",
    "ServeConfig": "torchft_tpu.serving",
    "ServeWorker": "torchft_tpu.serving",
    "SnapshotPublisher": "torchft_tpu.serving",
    "SnapshotRegistry": "torchft_tpu.serving",
}

__all__ = ["__version__", *_EXPORTS]


def __getattr__(name: str):
    if name in _EXPORTS:
        import importlib

        return getattr(importlib.import_module(_EXPORTS[name]), name)
    raise AttributeError(f"module 'torchft_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(__all__)
