"""Fault-tolerant optimizer wrapper.

Role-equivalent of the reference OptimizerWrapper (torchft/optim.py:25-64):
``zero_grad() -> start_quorum`` and ``step() only if should_commit``. The JAX
version wraps an optax GradientTransformation: ``step`` applies the update
only when the commit vote succeeds, otherwise returns the inputs unchanged
(the step is discarded).
"""

from __future__ import annotations

from typing import Any, Tuple

import optax

from torchft_tpu.manager import Manager
from torchft_tpu.work import GradStream

__all__ = ["OptimizerWrapper"]


class OptimizerWrapper:
    """Usage (the heal-safe idiom — vote, then read state, then update)::

        optimizer = OptimizerWrapper(manager, optax.adamw(3e-4))
        state = {"params": params, "opt_state": optimizer.init(params)}
        # register state-dict fns that read/write `state` with the manager
        for batch in data:
            optimizer.start_step()            # zero_grad(): starts quorum
            grads = grad_fn(state["params"], batch)
            avg = manager.allreduce(grads).get_future().wait()
            if optimizer.commit():            # a live heal lands HERE
                state["params"], state["opt_state"] = optimizer.apply(
                    state["params"], state["opt_state"], avg
                )
    """

    def __init__(self, manager: Manager, tx: optax.GradientTransformation) -> None:
        self.manager = manager
        self.tx = tx

    def init(self, params: Any) -> optax.OptState:
        return self.tx.init(params)

    def start_step(self) -> None:
        """Call at the top of the step (reference zero_grad -> start_quorum)."""
        self.manager.start_quorum()

    # alias for API parity with the reference
    zero_grad = start_step

    def allreduce_gradients(
        self, grads: Any, should_quantize: bool = False
    ) -> GradStream:
        """Kick off a streamed managed allreduce for one microbatch's grads.

        Returns immediately with a :class:`GradStream`; buckets reduce and
        land while the caller computes the next microbatch. A
        gradient-accumulation loop issues one stream per microbatch and
        averages the ``wait()`` results after the last one — allreduce is
        linear, so mean-of-streamed-means equals reducing the accumulated
        mean, and every stream's wire rides under the next microbatch's
        grad_fn (see examples/train_ddp.py ``--grad-accum``).
        ``should_quantize=True`` streams the buckets compressed (fp8
        unless ``TORCHFT_COMPRESS`` picks int8) with per-bucket error
        feedback where the Manager supports it."""
        return self.manager.allreduce_streamed(
            grads, should_quantize=should_quantize
        )

    def commit(self) -> bool:
        """The commit vote alone (``manager.should_commit()``).

        Splitting the vote from the arithmetic matters in functional code:
        a live heal lands DURING the vote (the pending recovered state is
        written through the registered load_state_dict fn inside
        ``should_commit``), so params captured before the vote are stale on
        exactly the step that healed. Vote first, then read state and call
        :meth:`apply` — the mutable-dict idiom (docs/migration.md).
        """
        return self.manager.should_commit()

    def apply(
        self, params: Any, opt_state: optax.OptState, grads: Any
    ) -> Tuple[Any, optax.OptState]:
        """The optimizer arithmetic alone — call after :meth:`commit`
        returned True, with params/opt_state read AFTER the vote.

        (Named ``apply``, not ``update``: optax's ``tx.update`` takes
        ``(grads, opt_state, params)`` — a same-arity all-pytree signature
        with the outer arguments swapped relative to this params-first
        method. A name collision would let a misordered call run silently
        and train on garbage.)

        Non-participants (a replica that just healed under async quorum, a
        FIXED_WITH_SPARES spare) apply this too: their own contribution was
        zeroed but they RECEIVE the cohort's average (reference
        manager.py:441-451 — zero the input, join the collective, divide by
        num_participants), and applying the same update to the same healed
        entry-of-step params is precisely what keeps them in bitwise
        lockstep with the cohort (tests/test_flax_interop.py pins this).
        """
        import jax
        import jax.numpy as jnp

        grads = jax.tree_util.tree_map(jnp.asarray, grads)
        updates, new_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_state

    def step(
        self, params: Any, opt_state: optax.OptState, grads: Any
    ) -> Tuple[Any, optax.OptState, bool]:
        """Vote + update in one call (reference torchft/optim.py:52-55).

        Returns (params, opt_state, committed); on a failed vote both are
        returned unchanged and the step is discarded.

        CAVEAT: ``params``/``opt_state`` were necessarily read before the
        vote, so on a step that live-healed this replica the update is
        applied to stale inputs. Loops that can heal (any loop under a
        Manager with peers) should use ``commit()`` + ``apply()`` with
        post-vote reads instead; ``step()`` is fine for spare-less,
        heal-free settings and mirrors the reference API.
        """
        if not self.manager.should_commit():
            return params, opt_state, False
        new_params, new_state = self.apply(params, opt_state, grads)
        return new_params, new_state, True
