"""Fault-tolerant optimizer wrapper.

Role-equivalent of the reference OptimizerWrapper (torchft/optim.py:25-64):
``zero_grad() -> start_quorum`` and ``step() only if should_commit``. The JAX
version wraps an optax GradientTransformation: ``step`` applies the update
only when the commit vote succeeds, otherwise returns the inputs unchanged
(the step is discarded).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import optax

from torchft_tpu.manager import Manager

__all__ = ["OptimizerWrapper"]


class OptimizerWrapper:
    """Usage::

        optimizer = OptimizerWrapper(manager, optax.adamw(3e-4))
        opt_state = optimizer.init(params)
        for batch in data:
            optimizer.start_step()            # zero_grad(): starts quorum
            grads = grad_fn(params, batch)
            avg = manager.allreduce(grads).get_future().wait()
            params, opt_state, committed = optimizer.step(params, opt_state, avg)
    """

    def __init__(self, manager: Manager, tx: optax.GradientTransformation) -> None:
        self.manager = manager
        self.tx = tx

    def init(self, params: Any) -> optax.OptState:
        return self.tx.init(params)

    def start_step(self) -> None:
        """Call at the top of the step (reference zero_grad -> start_quorum)."""
        self.manager.start_quorum()

    # alias for API parity with the reference
    zero_grad = start_step

    def step(
        self, params: Any, opt_state: optax.OptState, grads: Any
    ) -> Tuple[Any, optax.OptState, bool]:
        """Apply the update iff the replica group's commit vote succeeds.

        Returns (params, opt_state, committed); on a failed vote both params
        and opt_state are returned unchanged and the step is discarded.
        """
        if not self.manager.should_commit():
            return params, opt_state, False
        import jax
        import jax.numpy as jnp

        grads = jax.tree_util.tree_map(jnp.asarray, grads)
        updates, new_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_state, True
