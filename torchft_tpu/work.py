"""Asynchronous work handles for collective operations.

The reference framework returns ``torch.distributed.Work`` objects from its
process groups (reference: torchft/work.py:15-26, torchft/process_group.py).
JAX has no user-visible streams or Work objects — dispatch is asynchronous by
default and ordering is handled by the runtime — so this module defines a
small, framework-independent ``Future``/``Work`` pair that the rest of the
stack (process groups, the Manager, checkpoint transports) uses to represent
in-flight host- or device-side operations.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Generic, List, Optional, TypeVar

T = TypeVar("T")
S = TypeVar("S")

__all__ = [
    "Future",
    "Work",
    "DummyWork",
    "FutureWork",
    "GradStream",
    "join_futures",
]


class Future(Generic[T]):
    """A minimal thread-safe future with callback chaining.

    Mirrors the subset of ``torch.futures.Future`` the reference relies on
    (``value``, ``wait``, ``then``, ``set_result``, ``set_exception``) without
    any torch dependency.
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._done = False
        self._result: Optional[T] = None
        self._exception: Optional[BaseException] = None
        self._callbacks: List[Callable[["Future[T]"], None]] = []

    # -- completion -------------------------------------------------------
    def set_result(self, result: T) -> None:
        with self._cond:
            if self._done:
                raise RuntimeError("future already completed")
            self._result = result
            self._done = True
            callbacks = list(self._callbacks)
            self._callbacks.clear()
            self._cond.notify_all()
        for cb in callbacks:
            self._invoke(cb)

    def set_exception(self, exc: BaseException) -> None:
        with self._cond:
            if self._done:
                raise RuntimeError("future already completed")
            self._exception = exc
            self._done = True
            callbacks = list(self._callbacks)
            self._callbacks.clear()
            self._cond.notify_all()
        for cb in callbacks:
            self._invoke(cb)

    def _invoke(self, cb: Callable[["Future[T]"], None]) -> None:
        try:
            cb(self)
        except Exception:  # callbacks must never break completion
            import logging

            logging.getLogger(__name__).exception("future callback failed")

    # -- inspection -------------------------------------------------------
    def done(self) -> bool:
        with self._cond:
            return self._done

    def exception(self) -> Optional[BaseException]:
        with self._cond:
            return self._exception

    def wait(self, timeout: Optional[float] = None) -> T:
        """Block until complete; raises the stored exception if any."""
        with self._cond:
            if not self._cond.wait_for(lambda: self._done, timeout=timeout):
                raise TimeoutError(f"future did not complete within {timeout}s")
            if self._exception is not None:
                raise self._exception
            return self._result  # type: ignore[return-value]

    def value(self) -> T:
        """Non-blocking result access; requires ``done()``."""
        with self._cond:
            if not self._done:
                raise RuntimeError("future is not complete")
            if self._exception is not None:
                raise self._exception
            return self._result  # type: ignore[return-value]

    # -- chaining ---------------------------------------------------------
    def add_done_callback(self, cb: Callable[["Future[T]"], None]) -> None:
        with self._cond:
            if not self._done:
                self._callbacks.append(cb)
                return
        self._invoke(cb)

    def then(self, cb: Callable[["Future[T]"], S]) -> "Future[S]":
        """Return a new future holding ``cb(self)`` once this completes.

        Unlike torch's ``then``, the callback receives the *completed* future
        (same convention as torch) and its return value resolves the chained
        future; exceptions propagate.
        """
        out: Future[S] = Future()

        def _run(fut: "Future[T]") -> None:
            try:
                out.set_result(cb(fut))
            except BaseException as e:  # noqa: BLE001 - propagate everything
                out.set_exception(e)

        self.add_done_callback(_run)
        return out

    @staticmethod
    def completed(value: T) -> "Future[T]":
        f: Future[T] = Future()
        f.set_result(value)
        return f


class Work:
    """Handle for an in-flight collective operation."""

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the op (and its future chain) completes."""
        raise NotImplementedError

    def get_future(self) -> Future[Any]:
        raise NotImplementedError

    def exception(self) -> Optional[BaseException]:
        fut = self.get_future()
        return fut.exception() if fut.done() else None

    def synchronize(self) -> None:
        """Ensure device-side effects are ordered; default is wait()."""
        self.wait()


class DummyWork(Work):
    """Pre-completed work returning a fixed result.

    Used after swallowed errors and by the dummy process group
    (reference behavior: torchft/work.py:15-26).
    """

    def __init__(self, result: Any = None) -> None:
        self._future: Future[Any] = Future.completed(result)

    def wait(self, timeout: Optional[float] = None) -> bool:
        self._future.wait(timeout)
        return True

    def get_future(self) -> Future[Any]:
        return self._future


class FutureWork(Work):
    """Work wrapping an arbitrary Future."""

    def __init__(self, future: Future[Any]) -> None:
        self._future = future

    def wait(self, timeout: Optional[float] = None) -> bool:
        self._future.wait(timeout)
        return True

    def get_future(self) -> Future[Any]:
        return self._future


def join_futures(futures: List[Future[Any]]) -> Future[List[Any]]:
    """Join futures into one that resolves to ``[f.value() for f in futures]``.

    Fails fast: the first input exception resolves the joined future with that
    exception (later results are dropped). An empty list resolves immediately.
    """
    out: Future[List[Any]] = Future()
    if not futures:
        out.set_result([])
        return out

    remaining = [len(futures)]
    lock = threading.Lock()

    def _on_done(fut: Future[Any]) -> None:
        exc = fut.exception()
        if exc is not None:
            try:
                out.set_exception(exc)
            except RuntimeError:
                pass  # a sibling already failed the join
            return
        with lock:
            remaining[0] -= 1
            last = remaining[0] == 0
        if last:
            try:
                out.set_result([f.value() for f in futures])
            except RuntimeError:
                pass

    for f in futures:
        f.add_done_callback(_on_done)
    return out


class GradStream(Work):
    """Handle for a per-bucket streaming allreduce (Manager.allreduce_streamed).

    Exposes per-bucket completion (``ready(i)``) so gradient-accumulation
    loops can observe buckets landing while later microbatches still compute,
    plus an aggregate that joins every bucket.

    Deviation from the ``Work.wait -> bool`` convention: ``wait()`` returns
    the reduced pytree (zeros on swallowed communicator failure, mirroring
    ``manager.allreduce(...).get_future().wait()``) because that is the value
    callers of the streamed API want. ``get_future()`` returns the same
    aggregate future for Work-style chaining.
    """

    def __init__(
        self, bucket_futures: List[Future[Any]], aggregate: Future[Any]
    ) -> None:
        self._bucket_futures = list(bucket_futures)
        self._aggregate = aggregate

    def __len__(self) -> int:
        return len(self._bucket_futures)

    @property
    def num_buckets(self) -> int:
        return len(self._bucket_futures)

    def ready(self, i: int) -> bool:
        """True once bucket ``i`` has reduced, unpacked, and landed on device.

        A bucket that failed (or never completes after a mid-stream error)
        reports ``False``; per-bucket results are only exposed through the
        aggregate so a failed stream cannot leak partially-applied buckets.
        """
        fut = self._bucket_futures[i]
        return fut.done() and fut.exception() is None

    def wait(self, timeout: Optional[float] = None) -> Any:
        """Block until every bucket lands; returns the reduced pytree."""
        return self._aggregate.wait(timeout)

    def get_future(self) -> Future[Any]:
        return self._aggregate
