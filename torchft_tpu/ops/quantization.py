"""Rowwise-scaled fp8 quantization for compressed collectives.

Role-equivalent of the reference's Triton kernels
(torchft/quantization.py:53-686 — its only GPU-kernel code): fused rowwise
quantize/dequantize used by the quantized allreduce. The TPU equivalents are
Pallas kernels (fused_quantize_fp8 / fused_dequantize_fp8) plus plain numpy
host helpers used by the host TCP collectives.

Layout: values are viewed as rows of ``row`` elements (padded); each row gets
one f32 scale = amax/448 (float8_e4m3 max normal). The wire format keeps the
fp8 payload and the f32 scales as separate arrays rather than the reference's
interleaved flat buffer — on TPU, separate dense arrays stay tileable by XLA.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional, Tuple

import numpy as np

try:  # ml_dtypes ships with jax
    import ml_dtypes

    _FP8 = np.dtype(ml_dtypes.float8_e4m3fn)
except Exception:  # pragma: no cover - ml_dtypes is a jax dependency
    _FP8 = None

FP8_MAX = 448.0  # float8_e4m3fn max normal value
INT8_MAX = 127.0

COMPRESS_ENV = "TORCHFT_COMPRESS"
COMPRESS_MODES = ("off", "fp8", "int8")

__all__ = [
    "quantize_fp8_rowwise",
    "dequantize_fp8_rowwise",
    "quantize_int8_rowwise",
    "dequantize_int8_rowwise",
    "fused_quantize_fp8",
    "fused_dequantize_fp8",
    "CompressedWire",
    "is_compressed_wire",
    "codec",
    "resolve_compress_mode",
    "compress_bucket",
    "decompress_bucket",
    "COMPRESS_ENV",
    "COMPRESS_MODES",
]


# ---------------------------------------------------------------------------
# Host (numpy) path — used by ProcessGroupHost quantized collectives
# ---------------------------------------------------------------------------
def _pad_rows(flat: np.ndarray, row: int) -> Tuple[np.ndarray, int, int]:
    """View ``flat`` as a (rows, row) f32 matrix, zero-padding the tail.

    The hot path (bucket sizes that are exact row multiples, which is every
    bucket the packer cuts except possibly the last) is a zero-copy reshape;
    only ragged tails pay the pad-and-copy.
    """
    flat = np.ascontiguousarray(flat, dtype=np.float32).reshape(-1)
    n = flat.size
    rows = max(1, -(-n // row))
    if n == rows * row:
        return flat.reshape(rows, row), rows, n
    padded = np.zeros(rows * row, dtype=np.float32)
    padded[:n] = flat
    return padded.reshape(rows, row), rows, n


@functools.lru_cache(maxsize=1)
def _fp8_dequant_lut() -> np.ndarray:
    """All 256 float8_e4m3fn values as f32, indexed by bit pattern.

    A table lookup decodes ~2x faster than ml_dtypes' elementwise cast on
    host CPUs and is bit-identical by construction (the table IS the cast).
    """
    assert _FP8 is not None
    return np.arange(256, dtype=np.uint8).view(_FP8).astype(np.float32)


def quantize_fp8_rowwise(
    flat: np.ndarray, row: int = 512
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Quantize a flat f32/bf16 array to (fp8 payload, f32 row scales, n).

    The payload is returned as uint8 (fp8 bit pattern) so it pickles/ships
    compactly; ``n`` is the unpadded element count.
    """
    assert _FP8 is not None, "ml_dtypes with float8_e4m3fn is required"
    mat, rows, n = _pad_rows(flat, row)
    amax = np.max(np.abs(mat), axis=1, keepdims=True)
    scales = np.where(amax > 0, amax / FP8_MAX, 1.0).astype(np.float32)
    # multiply by the reciprocal: one rows-long divide instead of an
    # elements-long one (broadcast multiplies are cheaper than divides)
    q = (mat * (np.float32(1.0) / scales)).astype(_FP8)
    return q.view(np.uint8), scales[:, 0], n


def dequantize_fp8_rowwise(
    payload: np.ndarray, scales: np.ndarray, n: int, dtype=np.float32
) -> np.ndarray:
    """Inverse of quantize_fp8_rowwise; returns a flat array of length n."""
    assert _FP8 is not None
    # accept both engines' scale shapes — (rows,) host vs (rows, 1) fused —
    # a (rows, 1) input would otherwise broadcast to (rows, rows, row) and
    # silently return truncated garbage
    scales = np.asarray(scales).reshape(-1)
    mat = _fp8_dequant_lut()[payload.reshape(scales.size, -1)]
    mat *= scales[:, None]
    out = mat.reshape(-1)[:n]
    return out if dtype == np.float32 else out.astype(dtype)


def quantize_int8_rowwise(
    flat: np.ndarray, row: int = 512
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Symmetric rowwise int8: (int8 payload viewed uint8, f32 scales, n).

    Same layout contract as the fp8 codec (rows of ``row`` elements, one
    f32 scale per row = amax/127) so the two are interchangeable on the
    compressed wire.
    """
    mat, rows, n = _pad_rows(flat, row)
    amax = np.max(np.abs(mat), axis=1, keepdims=True)
    all_finite = bool(np.isfinite(amax).all())
    # non-finite rows (inf/nan) would poison rint(); saturate them at the
    # largest finite magnitude in the row instead of propagating nan codes
    finite_amax = (
        amax if all_finite
        else np.where(np.isfinite(amax), amax, np.float32(0.0))
    )
    scales = np.where(finite_amax > 0, finite_amax / INT8_MAX, 1.0).astype(
        np.float32
    )
    q = mat * (np.float32(1.0) / scales)
    np.rint(q, out=q)
    np.clip(q, -INT8_MAX, INT8_MAX, out=q)
    if not all_finite:
        # amax propagates any inf/nan in its row, so an all-finite amax
        # proves the whole matrix is finite and this pass can be skipped
        q = np.nan_to_num(q, nan=0.0, posinf=INT8_MAX, neginf=-INT8_MAX)
    q = q.astype(np.int8)
    return q.view(np.uint8), scales[:, 0], n


def dequantize_int8_rowwise(
    payload: np.ndarray, scales: np.ndarray, n: int, dtype=np.float32
) -> np.ndarray:
    """Inverse of quantize_int8_rowwise; returns a flat array of length n."""
    scales = np.asarray(scales).reshape(-1)
    mat = payload.view(np.int8).reshape(scales.size, -1).astype(np.float32)
    mat *= scales[:, None]
    out = mat.reshape(-1)[:n]
    return out if dtype == np.float32 else out.astype(dtype)


# ---------------------------------------------------------------------------
# Compressed-wire surface — per-bucket codec used by the streaming pipeline
# and the host compressed ring (process_group._ring_allreduce_compressed)
# ---------------------------------------------------------------------------
class CompressedWire(NamedTuple):
    """One bucket's compressed payload as it rides the host wire.

    A NamedTuple (not a class) on purpose: ``process_group._to_host`` and
    the full-mesh exchange path pass tuples through untouched, so the wire
    survives every PG boundary without special-casing.
    """

    mode: str  # "fp8" | "int8"
    payload: np.ndarray  # (rows, row) uint8 bit patterns of the codes
    scales: np.ndarray  # (rows,) f32 rowwise scales
    n: int  # unpadded element count
    dtype: str  # original dtype str, restored on decompress
    row: int  # row length the scales are keyed to


def is_compressed_wire(x) -> bool:
    return isinstance(x, CompressedWire)


def codec(mode: str):
    """(quantize, dequantize) pair for a compress mode."""
    if mode == "fp8":
        return quantize_fp8_rowwise, dequantize_fp8_rowwise
    if mode == "int8":
        return quantize_int8_rowwise, dequantize_int8_rowwise
    raise ValueError(f"no codec for compress mode {mode!r}")


def resolve_compress_mode(mode: Optional[str] = None) -> str:
    """Resolve the wire-compression mode: env > constructor arg > "off".

    Raises ValueError (with the valid set) on a bad value — doctor.py's
    compress-env check funnels through here so the CLI and the Manager
    reject identically.
    """
    # knobs.env_raw (not os.environ) so a policy-plane override on
    # TORCHFT_COMPRESS retargets the codec live, and still beats a
    # stale ambient env var the operator exported at launch.
    from torchft_tpu import knobs

    raw = knobs.env_raw(COMPRESS_ENV)
    if raw is not None:
        value = raw.strip().lower() or "off"
    elif mode is not None:
        value = str(mode).strip().lower() or "off"
    else:
        value = "off"
    if value not in COMPRESS_MODES:
        raise ValueError(
            f"invalid compress mode {value!r} (from {COMPRESS_ENV} or "
            f"constructor): expected one of {COMPRESS_MODES}"
        )
    return value


def compress_bucket(
    flat: np.ndarray, mode: str, row: int = 512, dtype=None
) -> CompressedWire:
    """Quantize one flat host bucket into a CompressedWire."""
    quantize, _ = codec(mode)
    out_dtype = np.dtype(dtype if dtype is not None else flat.dtype)
    payload, scales, n = quantize(flat, row=row)
    return CompressedWire(
        mode=mode,
        payload=payload,
        scales=scales,
        n=n,
        # .name (not .str) round-trips ml_dtypes extended dtypes (bfloat16)
        dtype=out_dtype.name,
        row=row,
    )


def decompress_bucket(wire: CompressedWire, dtype=None) -> np.ndarray:
    """Inverse of compress_bucket; flat array of length ``wire.n``."""
    _, dequantize = codec(wire.mode)
    out_dtype = np.dtype(dtype if dtype is not None else wire.dtype)
    return dequantize(wire.payload, wire.scales, wire.n, dtype=out_dtype)


# ---------------------------------------------------------------------------
# Device (Pallas) path — fused kernels for on-device quantization
# ---------------------------------------------------------------------------
def _quantize_kernel(x_ref, q_ref, scale_ref):
    x = x_ref[...].astype(jnp_f32())
    amax = jnp().max(jnp().abs(x), axis=-1, keepdims=True)
    scale = jnp().where(amax > 0, amax / FP8_MAX, 1.0)
    q_ref[...] = (x / scale).astype(jnp().float8_e4m3fn)
    scale_ref[...] = scale[:, :1]


def _dequantize_kernel(q_ref, scale_ref, out_ref):
    q = q_ref[...].astype(jnp_f32())
    out_ref[...] = q * scale_ref[...].astype(jnp_f32())


@functools.lru_cache(None)
def jnp():
    import jax.numpy as jnp

    return jnp


def jnp_f32():
    return jnp().float32


def _use_interpret() -> bool:
    import jax

    return jax.default_backend() not in ("tpu",)


def fused_quantize_fp8(x, row: int = 512):
    """Pallas: quantize a device array to (fp8[rows,row], scales f32[rows,1], n).

    Rows map onto the VPU lane layout; one grid step per row-block keeps the
    whole row in VMEM (see /opt/skills/guides/pallas_guide.md tiling rules).
    Falls back to interpret mode off-TPU so the same code paths are testable
    on the CPU mesh.
    """
    import jax
    import jax.numpy as jnumpy
    from jax.experimental import pallas as pl

    flat = x.reshape(-1).astype(jnumpy.float32)
    n = flat.size
    rows = max(1, -(-n // row))
    padded = jnumpy.zeros((rows * row,), jnumpy.float32).at[:n].set(flat)
    mat = padded.reshape(rows, row)

    block_rows = min(rows, 256)
    grid = (-(-rows // block_rows),)
    q, scales = pl.pallas_call(
        _quantize_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((block_rows, row), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((block_rows, row), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, row), jnumpy.float8_e4m3fn),
            jax.ShapeDtypeStruct((rows, 1), jnumpy.float32),
        ],
        interpret=_use_interpret(),
    )(mat)
    return q, scales, n


def fused_dequantize_fp8(q, scales, n: int, row: int = 512):
    """Pallas: inverse of fused_quantize_fp8; returns flat f32 of length n."""
    import jax
    import jax.numpy as jnumpy
    from jax.experimental import pallas as pl

    rows = q.shape[0]
    block_rows = min(rows, 256)
    grid = (-(-rows // block_rows),)
    out = pl.pallas_call(
        _dequantize_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, row), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, row), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, row), jnumpy.float32),
        interpret=_use_interpret(),
    )(q, scales)
    return out.reshape(-1)[:n]
