"""Rowwise-scaled fp8 quantization for compressed collectives.

Role-equivalent of the reference's Triton kernels
(torchft/quantization.py:53-686 — its only GPU-kernel code): fused rowwise
quantize/dequantize used by the quantized allreduce. The TPU equivalents are
Pallas kernels (fused_quantize_fp8 / fused_dequantize_fp8) plus plain numpy
host helpers used by the host TCP collectives.

Layout: values are viewed as rows of ``row`` elements (padded); each row gets
one f32 scale = amax/448 (float8_e4m3 max normal). The wire format keeps the
fp8 payload and the f32 scales as separate arrays rather than the reference's
interleaved flat buffer — on TPU, separate dense arrays stay tileable by XLA.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

try:  # ml_dtypes ships with jax
    import ml_dtypes

    _FP8 = np.dtype(ml_dtypes.float8_e4m3fn)
except Exception:  # pragma: no cover - ml_dtypes is a jax dependency
    _FP8 = None

FP8_MAX = 448.0  # float8_e4m3fn max normal value

__all__ = [
    "quantize_fp8_rowwise",
    "dequantize_fp8_rowwise",
    "fused_quantize_fp8",
    "fused_dequantize_fp8",
]


# ---------------------------------------------------------------------------
# Host (numpy) path — used by ProcessGroupHost quantized collectives
# ---------------------------------------------------------------------------
def quantize_fp8_rowwise(
    flat: np.ndarray, row: int = 512
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Quantize a flat f32/bf16 array to (fp8 payload, f32 row scales, n).

    The payload is returned as uint8 (fp8 bit pattern) so it pickles/ships
    compactly; ``n`` is the unpadded element count.
    """
    assert _FP8 is not None, "ml_dtypes with float8_e4m3fn is required"
    flat = np.ascontiguousarray(flat, dtype=np.float32).reshape(-1)
    n = flat.size
    rows = max(1, -(-n // row))
    padded = np.zeros(rows * row, dtype=np.float32)
    padded[:n] = flat
    mat = padded.reshape(rows, row)
    amax = np.max(np.abs(mat), axis=1, keepdims=True)
    scales = np.where(amax > 0, amax / FP8_MAX, 1.0).astype(np.float32)
    q = (mat / scales).astype(_FP8)
    return q.view(np.uint8), scales[:, 0], n


def dequantize_fp8_rowwise(
    payload: np.ndarray, scales: np.ndarray, n: int, dtype=np.float32
) -> np.ndarray:
    """Inverse of quantize_fp8_rowwise; returns a flat array of length n."""
    assert _FP8 is not None
    q = payload.view(_FP8)
    # accept both engines' scale shapes — (rows,) host vs (rows, 1) fused —
    # a (rows, 1) input would otherwise broadcast to (rows, rows, row) and
    # silently return truncated garbage
    scales = np.asarray(scales).reshape(-1)
    mat = q.astype(np.float32) * scales[:, None].astype(np.float32)
    return mat.reshape(-1)[:n].astype(dtype)


# ---------------------------------------------------------------------------
# Device (Pallas) path — fused kernels for on-device quantization
# ---------------------------------------------------------------------------
def _quantize_kernel(x_ref, q_ref, scale_ref):
    x = x_ref[...].astype(jnp_f32())
    amax = jnp().max(jnp().abs(x), axis=-1, keepdims=True)
    scale = jnp().where(amax > 0, amax / FP8_MAX, 1.0)
    q_ref[...] = (x / scale).astype(jnp().float8_e4m3fn)
    scale_ref[...] = scale[:, :1]


def _dequantize_kernel(q_ref, scale_ref, out_ref):
    q = q_ref[...].astype(jnp_f32())
    out_ref[...] = q * scale_ref[...].astype(jnp_f32())


@functools.lru_cache(None)
def jnp():
    import jax.numpy as jnp

    return jnp


def jnp_f32():
    return jnp().float32


def _use_interpret() -> bool:
    import jax

    return jax.default_backend() not in ("tpu",)


def fused_quantize_fp8(x, row: int = 512):
    """Pallas: quantize a device array to (fp8[rows,row], scales f32[rows,1], n).

    Rows map onto the VPU lane layout; one grid step per row-block keeps the
    whole row in VMEM (see /opt/skills/guides/pallas_guide.md tiling rules).
    Falls back to interpret mode off-TPU so the same code paths are testable
    on the CPU mesh.
    """
    import jax
    import jax.numpy as jnumpy
    from jax.experimental import pallas as pl

    flat = x.reshape(-1).astype(jnumpy.float32)
    n = flat.size
    rows = max(1, -(-n // row))
    padded = jnumpy.zeros((rows * row,), jnumpy.float32).at[:n].set(flat)
    mat = padded.reshape(rows, row)

    block_rows = min(rows, 256)
    grid = (-(-rows // block_rows),)
    q, scales = pl.pallas_call(
        _quantize_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((block_rows, row), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((block_rows, row), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, row), jnumpy.float8_e4m3fn),
            jax.ShapeDtypeStruct((rows, 1), jnumpy.float32),
        ],
        interpret=_use_interpret(),
    )(mat)
    return q, scales, n


def fused_dequantize_fp8(q, scales, n: int, row: int = 512):
    """Pallas: inverse of fused_quantize_fp8; returns flat f32 of length n."""
    import jax
    import jax.numpy as jnumpy
    from jax.experimental import pallas as pl

    rows = q.shape[0]
    block_rows = min(rows, 256)
    grid = (-(-rows // block_rows),)
    out = pl.pallas_call(
        _dequantize_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, row), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, row), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, row), jnumpy.float32),
        interpret=_use_interpret(),
    )(q, scales)
    return out.reshape(-1)[:n]
