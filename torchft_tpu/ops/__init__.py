from torchft_tpu.ops.quantization import (
    dequantize_fp8_rowwise,
    fused_dequantize_fp8,
    fused_quantize_fp8,
    quantize_fp8_rowwise,
)

__all__ = [
    "quantize_fp8_rowwise",
    "dequantize_fp8_rowwise",
    "fused_quantize_fp8",
    "fused_dequantize_fp8",
]
