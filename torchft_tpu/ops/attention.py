"""Fused causal attention for the training hot path.

The reference has no attention kernels of its own (it trains via torchtitan,
whose SDPA/flash comes from PyTorch); in a standalone TPU framework the
attention kernel is ours to own. On TPU this dispatches to the Pallas
flash-attention kernel (tiled online-softmax, never materializes the S x S
score matrix in HBM — the O(S) memory path that makes long sequences and big
batches fit); elsewhere (CPU tests, virtual-device dryruns) it falls back to
a plain XLA implementation with identical semantics.

Layout contract matches torchft_tpu.models.llama: q [B, S, Hq, hd],
k/v [B, S, Hkv, hd] (GQA: Hq a multiple of Hkv), causal, scaled by
1/sqrt(hd). Output [B, S, Hq, hd].
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["causal_attention", "xla_attention", "flash_attention_tpu"]


def _repeat_kv(q: jax.Array, k: jax.Array, v: jax.Array):
    groups = q.shape[2] // k.shape[2]
    if groups > 1:
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)
    return k, v


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array, cfg: Any) -> jax.Array:
    """Plain XLA causal GQA attention (materialized scores, f32 softmax)."""
    hd = q.shape[-1]
    k, v = _repeat_kv(q, k, v)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    S = q.shape[1]
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def flash_attention_tpu(
    q: jax.Array, k: jax.Array, v: jax.Array, cfg: Any
) -> jax.Array:
    """Pallas flash attention (TPU only; full custom-vjp fwd+bwd)."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention,
    )

    hd = q.shape[-1]
    k, v = _repeat_kv(q, k, v)
    # kernel layout is [B, H, S, hd]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    S = qt.shape[2]
    if S % 128 != 0:
        raise ValueError(f"flash attention requires seq_len % 128 == 0, got {S}")
    # largest MXU-friendly block that divides S
    blk = next(b for b in (512, 256, 128) if S % b == 0)
    block_sizes = BlockSizes(
        block_q=blk,
        block_k_major=blk,
        block_k=blk,
        block_b=1,
        block_q_major_dkv=blk,
        block_k_major_dkv=blk,
        block_k_dkv=blk,
        block_q_dkv=blk,
        block_k_major_dq=blk,
        block_k_dq=blk,
        block_q_dq=blk,
    )
    out = flash_attention(
        qt,
        kt,
        vt,
        causal=True,
        sm_scale=1.0 / math.sqrt(hd),
        block_sizes=block_sizes,
    )
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _on_tpu() -> bool:
    # not cached: the active backend can change in-process (e.g. a virtual
    # CPU device context during dryruns), and default_backend() is cheap
    return jax.default_backend() == "tpu"


def causal_attention(q: jax.Array, k: jax.Array, v: jax.Array, cfg: Any) -> jax.Array:
    """Backend-dispatching causal attention: Pallas flash on TPU (when the
    sequence tiles cleanly), XLA fallback elsewhere."""
    S, hd = q.shape[1], q.shape[-1]
    if _on_tpu() and S % 128 == 0 and hd in (64, 128, 256):
        return flash_attention_tpu(q, k, v, cfg)
    return xla_attention(q, k, v, cfg)
