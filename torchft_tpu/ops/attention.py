"""Fused causal attention for the training hot path.

The reference has no attention kernels of its own (it trains via torchtitan,
whose SDPA/flash comes from PyTorch); in a standalone TPU framework the
attention kernel is ours to own. On TPU this dispatches to the Pallas
flash-attention kernel (tiled online-softmax, never materializes the S x S
score matrix in HBM — the O(S) memory path that makes long sequences and big
batches fit); elsewhere (CPU tests, virtual-device dryruns) it falls back to
a plain XLA implementation with identical semantics.

Layout contract matches torchft_tpu.models.llama: q [B, S, Hq, hd],
k/v [B, S, Hkv, hd] (GQA: Hq a multiple of Hkv), causal, scaled by
1/sqrt(hd). Output [B, S, Hq, hd].
"""

from __future__ import annotations

import functools
import math
import os
from typing import Any

import jax
import jax.numpy as jnp

__all__ = [
    "causal_attention",
    "xla_attention",
    "flash_attention_tpu",
    "splash_attention_tpu",
]


def _repeat_kv(q: jax.Array, k: jax.Array, v: jax.Array):
    groups = q.shape[2] // k.shape[2]
    if groups > 1:
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)
    return k, v


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array, cfg: Any) -> jax.Array:
    """Plain XLA causal GQA attention (materialized scores, f32 softmax)."""
    hd = q.shape[-1]
    k, v = _repeat_kv(q, k, v)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    S = q.shape[1]
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def flash_attention_tpu(
    q: jax.Array, k: jax.Array, v: jax.Array, cfg: Any
) -> jax.Array:
    """Pallas flash attention (TPU only; full custom-vjp fwd+bwd)."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention,
    )

    hd = q.shape[-1]
    k, v = _repeat_kv(q, k, v)
    # kernel layout is [B, H, S, hd]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    S = qt.shape[2]
    if S % 128 != 0:
        raise ValueError(f"flash attention requires seq_len % 128 == 0, got {S}")
    # largest MXU-friendly block that divides S
    blk = next(b for b in (512, 256, 128) if S % b == 0)
    block_sizes = BlockSizes(
        block_q=blk,
        block_k_major=blk,
        block_k=blk,
        block_b=1,
        block_q_major_dkv=blk,
        block_k_major_dkv=blk,
        block_k_dkv=blk,
        block_q_dkv=blk,
        block_k_major_dq=blk,
        block_k_dq=blk,
        block_q_dq=blk,
    )
    out = flash_attention(
        qt,
        kt,
        vt,
        causal=True,
        sm_scale=1.0 / math.sqrt(hd),
        block_sizes=block_sizes,
    )
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


@functools.lru_cache(maxsize=16)
def _splash_kernel(n_q_heads: int, seq_len: int, block: int, block_kv: int,
                   interpret: bool):
    """Build (and cache) a splash-attention kernel: mask construction and
    kernel specialization are trace-time work worth amortizing.

    ``block`` tiles the query dimension, ``block_kv`` the key/value
    dimension (asymmetric tiles let a sweep trade VMEM pressure on the KV
    side against online-softmax bookkeeping on the Q side).

    Construction runs under ``ensure_compile_time_eval``: the kernel bakes
    mask partials as arrays, and if those were created inside an outer trace
    (first call typically happens inside a remat'd scan body) the cache
    would leak that trace's tracers into every later jaxpr.
    """
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    mask = sm.MultiHeadMask(
        [sm.CausalMask((seq_len, seq_len))] * n_q_heads
    )
    block = min(block, seq_len)
    block_kv = min(block_kv, seq_len)
    bs = sk.BlockSizes(
        block_q=block,
        block_kv=block_kv,
        block_kv_compute=block_kv,
        block_q_dkv=block,
        block_kv_dkv=block_kv,
        block_kv_dkv_compute=block_kv,
        block_q_dq=block,
        block_kv_dq=block_kv,
    )
    with jax.ensure_compile_time_eval():
        return sk.make_splash_mha(
            mask=mask,
            block_sizes=bs,
            head_shards=1,
            q_seq_shards=1,
            interpret=interpret,
        )


def splash_attention_tpu(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    cfg: Any,
    interpret: bool = False,
) -> jax.Array:
    """GQA-native splash attention (fwd+bwd Pallas kernels).

    Unlike `flash_attention_tpu` this never materializes the repeated K/V
    heads: the kernel maps query-head groups onto shared KV heads directly,
    cutting attention HBM traffic by the GQA group factor (4x for the
    llama3 configs). The reference has no attention kernels of its own (it
    delegates to torchtitan/PyTorch SDPA); this is the framework's.
    """
    hd = q.shape[-1]
    # kernel layout is [heads, S, hd] per example; scale folded into q
    # (splash takes no sm_scale argument)
    scale = 1.0 / math.sqrt(hd)
    qt = (jnp.swapaxes(q, 1, 2) * jnp.asarray(scale, q.dtype))
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    S = qt.shape[2]
    # block 1024 is the measured winner on v5e (0.457 vs 0.449 MFU at 512;
    # 2048 fails to compile — round-4 sweep, docs/performance.md); larger
    # tiles amortize the online-softmax bookkeeping until VMEM runs out
    blk = next(b for b in (1024, 512, 256, 128) if S % b == 0)
    # benchmark escape hatch: benchmarks/mfu_sweep.py sweeps these to find
    # the best tiles for a given chip generation; training code leaves them
    # unset. BLOCK sets both dimensions, BLOCK_KV overrides the kv side.
    blk_env = os.environ.get("TORCHFT_TPU_SPLASH_BLOCK")
    if blk_env:
        blk = int(blk_env)
        if S % blk != 0:
            raise ValueError(
                f"TORCHFT_TPU_SPLASH_BLOCK={blk} does not divide seq_len {S}"
            )
    blk_kv = blk
    blk_kv_env = os.environ.get("TORCHFT_TPU_SPLASH_BLOCK_KV")
    if blk_kv_env:
        blk_kv = int(blk_kv_env)
        if S % blk_kv != 0:
            raise ValueError(
                f"TORCHFT_TPU_SPLASH_BLOCK_KV={blk_kv} does not divide "
                f"seq_len {S}"
            )
    kernel = _splash_kernel(qt.shape[1], S, blk, blk_kv, interpret)
    out = jax.vmap(kernel)(qt, kt, vt)  # [B, Hq, S, hd]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _on_tpu() -> bool:
    # not cached: the active backend can change in-process (e.g. a virtual
    # CPU device context during dryruns), and default_backend() is cheap
    return jax.default_backend() == "tpu"


# Which kernel the last causal_attention dispatch resolved to ("splash" /
# "flash" / "xla"). Set at trace time; benchmarks record it so a silent
# fallback to the slow path is visible in their artifacts, not just implied
# by the requested mode.
LAST_DISPATCH: "str | None" = None


def causal_attention(q: jax.Array, k: jax.Array, v: jax.Array, cfg: Any) -> jax.Array:
    """Backend-dispatching causal attention.

    On TPU (sequence tiling permitting): splash attention when the model is
    GQA/MQA (KV heads stay unrepeated — group-factor less HBM traffic),
    plain flash otherwise. XLA fallback elsewhere. Override with
    ``TORCHFT_TPU_ATTENTION=splash|flash|xla`` (benchmark escape hatch).
    """
    global LAST_DISPATCH
    S, hd = q.shape[1], q.shape[-1]
    tileable = S % 128 == 0 and hd in (64, 128, 256)
    choice = os.environ.get("TORCHFT_TPU_ATTENTION", "auto")
    if choice == "xla" or not (_on_tpu() and tileable):
        LAST_DISPATCH = "xla"
        return xla_attention(q, k, v, cfg)
    if choice == "splash" or (choice == "auto" and q.shape[2] != k.shape[2]):
        LAST_DISPATCH = "splash"
        return splash_attention_tpu(q, k, v, cfg)
    LAST_DISPATCH = "flash"
    return flash_attention_tpu(q, k, v, cfg)
