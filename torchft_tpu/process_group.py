"""Reconfigurable process groups: the fault-tolerant communication backend.

Role-equivalent of the reference's torchft/process_group.py (the shim over
NCCL/Gloo/XCCL that can be torn down and rebuilt per quorum without
restarting the process). The TPU-native design differs deliberately:

- **Immutable arrays.** JAX arrays cannot be mutated in place, so collectives
  return their results through the Work's future instead of writing into the
  input buffers. ``allreduce([x])`` yields a Work whose future resolves to the
  reduced arrays.
- **Two planes, like the reference.** ``ProcessGroupHost`` is the Gloo
  equivalent: CPU collectives over a full TCP mesh between replica groups,
  used for control data, tests, and as the DCN bridge for cross-replica-group
  traffic. Device arrays are staged host-side (device_get/device_put). The
  intra-replica-group plane (FSDP/TP shard dims) is *not* a process group at
  all on TPU — it is XLA SPMD over a jax.sharding.Mesh (see
  torchft_tpu/parallel/), exactly as the reference delegates intra-group
  parallelism to torchtitan (reference README.md:40).
- **Abort-based timeouts.** Collectives are issued on a dedicated dispatch
  thread per PG; timeouts arm a watchdog that calls ``abort()`` (closing the
  sockets), mirroring the reference's NCCL abort recovery
  (process_group.py:780-891).

Reconfiguration handshake matches the reference: ``configure(store_addr,
replica_rank, replica_world_size, ...)`` tears down the old communicator and
rendezvouses a new one via the KV store under a per-quorum prefix
(reference: manager.py:692-737).
"""

from __future__ import annotations

import enum
import logging
import pickle
import queue
import socket
import struct
import threading
import time
from abc import ABC, abstractmethod
from contextlib import contextmanager
from datetime import timedelta
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import torchft_tpu.flight_recorder as _fr
from torchft_tpu.coordination import KvClient
from torchft_tpu.futures import context_timeout
from torchft_tpu.work import DummyWork, Future, FutureWork, Work

logger = logging.getLogger(__name__)

__all__ = [
    "ReduceOp",
    "ProcessGroup",
    "ProcessGroupDummy",
    "ProcessGroupHost",
    "ProcessGroupBaby",
    "ProcessGroupBabyHost",
    "ErrorSwallowingProcessGroupWrapper",
    "FakeProcessGroupWrapper",
    "ManagedProcessGroup",
]


class ReduceOp(enum.Enum):
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PRODUCT = "product"


def _accum(op: ReduceOp, dst: np.ndarray, src: np.ndarray) -> None:
    """In-place elementwise accumulate — the one dispatch table shared by the
    full-mesh exchange (_reduce_np) and the ring (_ring_allreduce)."""
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        dst += src
    elif op == ReduceOp.MAX:
        np.maximum(dst, src, out=dst)
    elif op == ReduceOp.MIN:
        np.minimum(dst, src, out=dst)
    elif op == ReduceOp.PRODUCT:
        dst *= src
    else:
        raise ValueError(f"unsupported reduce op: {op}")


def _reduce_np(op: ReduceOp, bufs: List[np.ndarray]) -> np.ndarray:
    out = bufs[0].copy()
    for b in bufs[1:]:
        _accum(op, out, b)
    if op == ReduceOp.AVG:
        out = out / len(bufs)
    return out


def _copy_payload(h: Any) -> Any:
    """Independent copy of a wire payload: ndarray, or tuple containing
    ndarrays (the quantized (q, scales, n) format)."""
    if isinstance(h, np.ndarray):
        return h.copy()
    if isinstance(h, tuple):
        return tuple(
            x.copy() if isinstance(x, np.ndarray) else x for x in h
        )
    return h


def _to_host(x: Any) -> Any:
    """Stage a jax.Array (or array-like) to host memory.

    Tuples pass through untouched (the quantized collectives ship
    (payload, scales, n) tuples); everything else — including plain Python
    lists — is coerced to ndarray so the reduce math is well-defined.
    """
    if isinstance(x, np.ndarray):
        return x
    if isinstance(x, tuple):
        return x
    return np.asarray(x)


class ProcessGroup(ABC):
    """Abstract reconfigurable process group.

    API mirror of the reference ProcessGroup ABC (process_group.py:131-399)
    with JAX-style value-returning collectives.
    """

    def __init__(self) -> None:
        self._timeout: float = 60.0

    # -- lifecycle --------------------------------------------------------
    @abstractmethod
    def configure(
        self,
        store_addr: str,
        replica_rank: int,
        replica_world_size: int,
        quorum_id: int = 0,
    ) -> None:
        """(Re)initialize the communicator for a new quorum.

        ``store_addr`` is ``"host:port/prefix"`` into the rendezvous KV store;
        the prefix embeds the quorum id so concurrent reconfigurations never
        collide (reference: manager.py:703-705).
        """

    def prepare_configure(
        self,
        store_addr: str,
        replica_rank: int,
        replica_world_size: int,
        quorum_id: int = 0,
    ) -> Optional[Callable[[], None]]:
        """Two-phase configure: run everything that is safe off the main
        thread NOW and return the main-thread commit, or None when nothing
        needs the main thread.

        The Manager calls this from its quorum executor thread so the
        control-plane round-trip (rendezvous, membership barriers) overlaps
        the trainer's compute; whatever the returned callable does (e.g. a
        live jax-backend swap in ProcessGroupXLA's distributed mode) is
        applied by the Manager from the main thread at the next safe point.

        Default: the whole configure is prepare — host-plane PGs touch no
        global device runtime, so running configure on the quorum thread is
        already safe. Routed through ``self.configure`` (not a base
        implementation) so instance-attribute shadowing of ``configure``
        (timing wrappers, test mocks) keeps seeing every reconfigure.
        """
        self.configure(
            store_addr, replica_rank, replica_world_size, quorum_id=quorum_id
        )
        return None

    @abstractmethod
    def abort(self) -> None:
        """Hard-kill in-flight collectives; the PG stays errored until
        reconfigured."""

    @abstractmethod
    def shutdown(self) -> None:
        """Tear down cleanly (terminal)."""

    @abstractmethod
    def errored(self) -> Optional[Exception]:
        """Error state since last configure, if any."""

    @abstractmethod
    def size(self) -> int: ...

    @abstractmethod
    def rank(self) -> int: ...

    def set_timeout(self, timeout: "float | timedelta") -> None:
        self._timeout = (
            timeout.total_seconds() if isinstance(timeout, timedelta) else timeout
        )

    def getBackendName(self) -> str:
        return type(self).__name__

    # -- collectives ------------------------------------------------------
    @abstractmethod
    def allreduce(self, arrays: Sequence[Any], op: ReduceOp = ReduceOp.SUM) -> Work:
        """Future resolves to the reduced arrays (same structure as input)."""

    @abstractmethod
    def allgather(self, arrays: Sequence[Any]) -> Work:
        """Future resolves to a list (one per rank) of lists of arrays."""

    @abstractmethod
    def broadcast(self, arrays: Sequence[Any], root: int = 0) -> Work:
        """Future resolves to root's arrays on every rank."""

    @abstractmethod
    def reduce_scatter(
        self, input_chunks: Sequence[Sequence[Any]], op: ReduceOp = ReduceOp.SUM
    ) -> Work:
        """``input_chunks[r]`` is this rank's contribution destined for rank r;
        future resolves to the reduced chunk owned by this rank."""

    @abstractmethod
    def alltoall(self, input_chunks: Sequence[Any]) -> Work:
        """Future resolves to [chunk from rank 0, chunk from rank 1, ...]."""

    @abstractmethod
    def send(self, arrays: Sequence[Any], dst: int, tag: int = 0) -> Work: ...

    @abstractmethod
    def recv(self, src: int, tag: int = 0) -> Work:
        """Future resolves to the received arrays."""

    def barrier(self) -> Work:
        return self.allreduce([np.zeros((1,), dtype=np.float32)])


class ProcessGroupDummy(ProcessGroup):
    """World-size-1 no-op PG: collectives return their inputs.

    Reference: process_group.py:1005-1134 (used to soak up init broadcasts
    and in tests).
    """

    def __init__(self, rank: int = 0, world: int = 1) -> None:
        super().__init__()
        self._rank = rank
        self._world = world
        self.configure_count = 0

    def configure(self, store_addr, replica_rank, replica_world_size, quorum_id=0):
        self.configure_count += 1

    def abort(self) -> None:
        pass

    def shutdown(self) -> None:
        pass

    def errored(self) -> Optional[Exception]:
        return None

    def size(self) -> int:
        return self._world

    def rank(self) -> int:
        return self._rank

    def allreduce(self, arrays, op=ReduceOp.SUM):
        return DummyWork(list(arrays))

    def allgather(self, arrays):
        return DummyWork([list(arrays)])

    def broadcast(self, arrays, root=0):
        return DummyWork(list(arrays))

    def reduce_scatter(self, input_chunks, op=ReduceOp.SUM):
        return DummyWork(list(input_chunks[0]))

    def alltoall(self, input_chunks):
        return DummyWork(list(input_chunks))

    def send(self, arrays, dst, tag=0):
        return DummyWork(None)

    def recv(self, src, tag=0):
        return DummyWork(None)


# ---------------------------------------------------------------------------
# Host TCP mesh process group
# ---------------------------------------------------------------------------

_HDR = struct.Struct("!Q")


def _send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_HDR.pack(len(payload)) + payload)


def _recv_msg(sock: socket.socket) -> bytes:
    hdr = _recv_exact(sock, _HDR.size)
    (length,) = _HDR.unpack(hdr)
    return _recv_exact(sock, length)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed connection")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


class _Comm:
    """One generation of the TCP full mesh. Abort closes every socket, which
    makes all in-flight ops fail fast; a new generation is built on the next
    configure()."""

    def __init__(
        self,
        rank: int,
        world: int,
        store_addr: str,
        quorum_id: int,
        timeout: float,
    ) -> None:
        self.rank = rank
        self.world = world
        self.aborted = False
        self._lock = threading.Lock()
        self.peers: Dict[int, socket.socket] = {}
        # per-peer write serialization: collective writers (dispatch/ring
        # threads) and async p2p writers must never interleave frames on
        # one socket
        self._send_locks: Dict[int, threading.Lock] = {}
        self._p2p_queues: Dict[int, "queue.Queue"] = {}
        # persistent collective-writer worker (lazily started): ring hops and
        # full-mesh exchanges need a concurrent writer so symmetric
        # send/send never deadlocks on full TCP buffers, but spawning a
        # thread PER HOP charges every collective ~2 thread creations —
        # ruinous for the per-bucket streaming pipeline where a 16-bucket
        # plan is 16 ops instead of one. One long-lived worker fed by a
        # queue keeps the same concurrency at a queue-handoff price.
        self._coll_q: Optional["queue.Queue"] = None
        # traffic accounting (benchmarks/transport_bench.py asserts the ring
        # path's world-size-independent per-rank bytes from these)
        self.bytes_sent = 0
        self.bytes_recv = 0
        # send-side wire occupancy: seconds spent inside sendall pushing
        # frames into the link. Receive waits are deliberately NOT counted —
        # a recv blocked on a peer that is still computing would charge
        # compute time to the wire. bytes_sent / wire_busy_s is the
        # transport's delivered bandwidth (benchmarks use it for the
        # compressed-vs-raw effective-bandwidth comparison)
        self.wire_busy_s = 0.0
        # injected link faults: {frozenset({a, b}): fire_at_hop}. Shared by
        # reference with the owning ProcessGroupHost (configure() points this
        # at the PG-level dict) so tests can arm a fault before OR after the
        # generation exists. Checked only by the compressed ring's hop loop.
        self.link_faults: Dict[frozenset, int] = {}
        # per-comm compressed-collective sequence number: ops dispatch in the
        # same order on every rank (SPMD contract), so tagging hop frames
        # with (seq, attempt) lets a re-routed ring tell a stale frame from
        # a live one without a coordination round
        self.cring_seq = 0
        # links this comm has already seen die: later collectives start from
        # a topology that avoids them instead of re-discovering the failure
        # (a dead link stays avoided for the life of the generation)
        self.cring_dead: set = set()

        # store_addr is "host:port/prefix"; the prefix (set per-quorum and
        # per-group-rank by the Manager, reference manager.py:703-705) plus the
        # quorum id namespaces this generation's rendezvous keys.
        host_port, _, path = store_addr.partition("/")
        prefix = f"{path or 'pg'}/{quorum_id}"
        kv = KvClient(host_port, connect_timeout=timeout)

        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("0.0.0.0", 0))
        listener.listen(world)
        port = listener.getsockname()[1]
        self._listener = listener

        my_host = socket.gethostname()
        kv.set(f"{prefix}/addr_{rank}", f"{my_host}:{port}", timeout=timeout)

        # Deterministic connection pattern: rank i dials every j < i and
        # accepts from every j > i (with a hello byte carrying the dialer's
        # rank so accepts can arrive in any order).
        for j in range(rank):
            addr = kv.get(f"{prefix}/addr_{j}", timeout=timeout).decode()
            host, _, p = addr.rpartition(":")
            s = socket.create_connection((host, int(p)), timeout=timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _send_msg(s, pickle.dumps(("hello", rank)))
            self.peers[j] = s
        listener.settimeout(timeout)
        for _ in range(world - 1 - rank):
            s, _ = listener.accept()
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # accepted sockets need the op timeout too — dialed ones carry
            # it from create_connection; without this, waits on accepted
            # sockets are unbounded and set_timeout has nothing to update
            s.settimeout(timeout)
            tag, peer_rank = pickle.loads(_recv_msg(s))
            assert tag == "hello"
            self.peers[peer_rank] = s
        for j in self.peers:
            self._send_locks[j] = threading.Lock()

    def settimeout(self, timeout: float) -> None:
        with self._lock:
            for s in self.peers.values():
                try:
                    s.settimeout(timeout)
                except OSError:
                    pass

    def send_to(self, peer: int, obj: Any) -> None:
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        with self._send_locks[peer]:
            t0 = time.perf_counter()
            _send_msg(self.peers[peer], payload)
            # counters guarded by the send lock: multiple writer threads
            # (dispatch, ring, p2p) would race the read-modify-write
            self.wire_busy_s += time.perf_counter() - t0
            self.bytes_sent += len(payload) + _HDR.size

    def recv_from(self, peer: int) -> Any:
        payload = _recv_msg(self.peers[peer])
        self.bytes_recv += len(payload) + _HDR.size
        return pickle.loads(payload)

    def send_raw(self, peer: int, buf: Any) -> None:
        """Frame a raw buffer (no pickle, no concat copy): length header,
        then the bytes straight from the caller's memory. Typed ndarrays go
        through a uint8 view — memoryview can't export extended dtypes like
        ml_dtypes.bfloat16 (the dominant TPU gradient dtype)."""
        if isinstance(buf, np.ndarray):
            buf = buf.reshape(-1).view(np.uint8)  # reshape first: 0-d safe
        mv = memoryview(buf).cast("B")
        sock = self.peers[peer]
        with self._send_locks[peer]:
            t0 = time.perf_counter()
            sock.sendall(_HDR.pack(len(mv)))
            sock.sendall(mv)
            self.wire_busy_s += time.perf_counter() - t0
            self.bytes_sent += len(mv) + _HDR.size

    def recv_raw_into(self, peer: int, out: Any) -> None:
        """Receive one frame directly into a writable buffer (zero staging
        copies on the receive side)."""
        sock = self.peers[peer]
        (length,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
        if isinstance(out, np.ndarray):
            out = out.reshape(-1).view(np.uint8)
        mv = memoryview(out).cast("B")
        if length != len(mv):
            raise ValueError(f"frame size {length} != buffer size {len(mv)}")
        got = 0
        while got < length:
            n = sock.recv_into(mv[got:], min(length - got, 1 << 20))
            if n == 0:
                raise ConnectionError("peer closed connection")
            got += n
        self.bytes_recv += length + _HDR.size

    def check_link_fault(self, a: int, b: int, hop: int) -> None:
        """Raise ConnectionError if an injected fault covers link (a, b) at
        this hop. A fired fault stays armed — a dead link stays dead for the
        generation, which is exactly what forces the ring to re-form around
        it rather than retry through it."""
        at_hop = self.link_faults.get(frozenset((a, b)))
        if at_hop is not None and hop >= at_hop:
            raise ConnectionError(
                f"injected link failure {a}<->{b} at hop {hop}"
            )

    def recv_raw_discard(self, peer: int) -> int:
        """Read one raw frame from ``peer`` and throw the bytes away.

        Used by the compressed ring's re-route path to drain segment frames
        that belong to an aborted attempt (their pickled header was read,
        the raw payload behind it must not be left to corrupt the next
        attempt's frame stream). Returns the discarded byte count."""
        sock = self.peers[peer]
        (length,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
        got = 0
        scratch = bytearray(min(length, 1 << 20) or 1)
        mv = memoryview(scratch)
        while got < length:
            n = sock.recv_into(mv, min(length - got, len(scratch)))
            if n == 0:
                raise ConnectionError("peer closed connection")
            got += n
        self.bytes_recv += length + _HDR.size
        return length

    def _coll_writer_loop(self, q: "queue.Queue") -> None:
        while True:
            item = q.get()
            if item is None:
                return
            job, done, err = item
            try:
                job()
            except BaseException as e:  # noqa: BLE001
                err.append(e)
            finally:
                done.set()

    def submit_write(self, job: Callable[[], None]):
        """Run ``job`` on the persistent collective-writer thread; returns
        ``(done_event, err_list)``. Sentinel-safe vs abort: the aborted
        check and the enqueue share ``_lock`` with ``abort``'s sentinel
        post, so a job can never land behind the shutdown sentinel and
        leave its waiter blocked forever."""
        done = threading.Event()
        err: List[BaseException] = []
        with self._lock:
            if self.aborted:
                raise RuntimeError("communicator aborted")
            if self._coll_q is None:
                self._coll_q = queue.Queue()
                threading.Thread(
                    target=self._coll_writer_loop, args=(self._coll_q,),
                    daemon=True, name=f"pg_host_collwr_r{self.rank}",
                ).start()
            self._coll_q.put((job, done, err))
        return done, err

    def exchange(self, payloads: Dict[int, Any]) -> Dict[int, Any]:
        """Send payloads[r] to each rank r and receive one object from every
        peer. Deadlock-free: the collective-writer worker streams our sends
        while the caller thread drains receives."""

        def _writes() -> None:
            for peer in sorted(payloads):
                if peer != self.rank:
                    self.send_to(peer, payloads[peer])

        done, err = self.submit_write(_writes)
        out: Dict[int, Any] = {}
        if self.rank in payloads:
            out[self.rank] = payloads[self.rank]
        for peer in range(self.world):
            if peer == self.rank:
                continue
            out[peer] = self.recv_from(peer)
        done.wait()
        if err:
            raise err[0]
        return out

    def p2p_send_async(self, peer: int, job, fut, fail) -> None:
        """Run a p2p write job on the per-peer writer thread (strict FIFO
        per peer) instead of the dispatch thread. Rationale: symmetric
        send/send between two ranks would block both dispatch threads in
        sendall on full TCP buffers, and the matching recvs — queued behind
        them — could never drain (the deadlock the exchange/ring writer
        threads already guard against)."""
        import queue as _q

        def _writer(wq: "_q.Queue") -> None:
            while True:
                item = wq.get()
                if item is None:
                    return
                jb, ft, fl = item
                try:
                    jb()
                    ft.set_result(None)
                except BaseException as e:  # noqa: BLE001
                    err = e if isinstance(e, Exception) else RuntimeError(str(e))
                    fl(err)
                    try:
                        ft.set_exception(err)
                    except RuntimeError:
                        pass

        with self._lock:
            if self.aborted:
                raise RuntimeError("communicator aborted")
            q = self._p2p_queues.get(peer)
            if q is None:
                q = _q.Queue()
                self._p2p_queues[peer] = q
                threading.Thread(
                    target=_writer, args=(q,), daemon=True,
                    name=f"pg_host_p2p_r{self.rank}_to{peer}",
                ).start()
            # enqueue under the lock: abort() posts its shutdown sentinel
            # under the same lock, so a job can never land behind the
            # sentinel and leave its future unresolved
            q.put((job, fut, fail))

    def abort(self) -> None:
        with self._lock:
            self.aborted = True
            for q in self._p2p_queues.values():
                q.put(None)
            if self._coll_q is not None:
                self._coll_q.put(None)
            for s in self.peers.values():
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
            try:
                self._listener.close()
            except OSError:
                pass


# Payloads at or above this take the bandwidth-optimal ring; below it the
# full-mesh exchange wins on latency (one round-trip vs 2*(world-1)).
_RING_MIN_BYTES = 64 * 1024


def _ring_step(comm: "_Comm", right: int, left: int,
               send_buf: np.ndarray, recv_buf: np.ndarray) -> None:
    """One ring hop: stream our segment to the right neighbour while
    draining the left neighbour's into ``recv_buf``. The write rides the
    comm's persistent collective-writer worker because both sides send
    first — with synchronous sockets and multi-MB segments that would
    deadlock on full TCP buffers."""
    done, err = comm.submit_write(lambda: comm.send_raw(right, send_buf))
    comm.recv_raw_into(left, recv_buf)
    done.wait()
    if err:
        raise err[0]


def _ring_allreduce(comm: "_Comm", leaves: List[np.ndarray], op: ReduceOp) -> List[np.ndarray]:
    """Bandwidth-optimal allreduce: ring reduce-scatter + ring allgather.

    Per-rank traffic is 2*(world-1)/world * payload — independent of world
    size — versus the full-mesh exchange's (world-1) * payload (the
    round-1 data plane's O(world x bytes) weakness). Segments move as raw
    frames straight out of the flat working buffer: no pickling, and the
    same bytes are never serialized twice.

    Leaves are packed per dtype into one flat buffer each (gradients are
    almost always a single dtype, so this is one ring in practice), split
    into ``world`` segments, and unpacked at the end. Matches
    ``_reduce_np``'s semantics: accumulate in the input dtype, AVG divides
    by world at the end.
    """
    world, rank = comm.world, comm.rank
    right, left = (rank + 1) % world, (rank - 1) % world
    out: List[Optional[np.ndarray]] = [None] * len(leaves)

    groups: Dict[Any, List[int]] = {}
    for i, a in enumerate(leaves):
        groups.setdefault(a.dtype, []).append(i)

    for dtype, idxs in sorted(groups.items(), key=lambda kv: str(kv[0])):
        flat_len = sum(leaves[i].size for i in idxs)
        seg_len = max(1, -(-flat_len // world))
        buf = np.zeros(seg_len * world, dtype)
        ofs = 0
        for i in idxs:
            n = leaves[i].size
            buf[ofs:ofs + n] = leaves[i].ravel()
            ofs += n
        segs = buf.reshape(world, seg_len)
        recv_buf = np.empty(seg_len, dtype)

        # reduce-scatter: after world-1 hops, this rank holds the fully
        # reduced segment (rank+1) % world
        for step in range(world - 1):
            s_idx = (rank - step) % world
            r_idx = (rank - step - 1) % world
            _ring_step(comm, right, left, segs[s_idx], recv_buf)
            _accum(op, segs[r_idx], recv_buf)

        # allgather: circulate the reduced segments
        for step in range(world - 1):
            s_idx = (rank + 1 - step) % world
            r_idx = (rank - step) % world
            _ring_step(comm, right, left, segs[s_idx], segs[r_idx])

        if op == ReduceOp.AVG:
            if np.issubdtype(buf.dtype, np.integer):
                buf = buf / world  # float result, matching _reduce_np
            else:
                buf /= world

        ofs = 0
        for i in idxs:
            n = leaves[i].size
            # copy: returned leaves must be independent arrays (the exchange
            # path's contract) — views into the shared flat buffer would
            # alias each other under callers' in-place updates and pin the
            # whole padded buffer alive
            out[i] = buf[ofs:ofs + n].reshape(leaves[i].shape).copy()
            ofs += n

    return out  # type: ignore[return-value]


class _LinkFailure(Exception):
    """One ring hop's link is dead; carries the (lo, hi) rank pair."""

    def __init__(self, a: int, b: int) -> None:
        self.pair = (min(a, b), max(a, b))
        super().__init__(
            f"ring link {self.pair[0]}<->{self.pair[1]} failed"
        )


def _ring_order(world: int, dead: "set") -> Optional[List[int]]:
    """Deterministic rank ordering whose ring adjacencies (wraparound
    included) avoid every dead link. Every rank computes this from the same
    dead set, so the re-formed ring needs no extra coordination round.
    Returns None when no such ordering exists (e.g. world=2 with its only
    link dead)."""
    if not dead:
        return list(range(world))

    def _ok(order: List[int]) -> bool:
        return all(
            frozenset((order[i], order[(i + 1) % world])) not in dead
            for i in range(world)
        )

    base = list(range(world))
    if _ok(base):
        return base
    if world <= 8:
        import itertools

        # rotations of a valid cycle are the same ring, so pinning rank 0
        # first loses nothing and caps the search at (world-1)!
        for perm in itertools.permutations(range(1, world)):
            cand = [0, *perm]
            if _ok(cand):
                return cand
        return None
    # large worlds: greedy chain extension — dead links are few in practice,
    # and a miss here degrades to the pre-existing swallowed-step behavior
    order = [0]
    rest = list(range(1, world))
    while rest:
        nxt = next(
            (r for r in rest if frozenset((order[-1], r)) not in dead), None
        )
        if nxt is None:
            return None
        order.append(nxt)
        rest.remove(nxt)
    return order if _ok(order) else None


def _chain_order(world: int, dead: "set") -> Optional[List[int]]:
    """Hamiltonian path over healthy links — the fallback for dead-link
    sets that break every cycle but not every path. Any single dead link at
    world<=3 is in this class (a 3-cycle needs all three edges), so this is
    what makes small-world failover possible at all. Deterministic for the
    same reason as _ring_order."""
    def _ok(order) -> bool:
        return all(
            frozenset((order[i], order[i + 1])) not in dead
            for i in range(world - 1)
        )

    base = list(range(world))
    if _ok(base):
        return base
    if world <= 8:
        import itertools

        for perm in itertools.permutations(range(world)):
            if perm[0] > perm[-1]:
                continue  # a path equals its reverse; keep one canonical form
            if _ok(perm):
                return list(perm)
        return None
    order = [0]
    rest = list(range(1, world))
    while rest:
        nxt = next(
            (r for r in rest if frozenset((order[-1], r)) not in dead), None
        )
        if nxt is None:
            return None
        order.append(nxt)
        rest.remove(nxt)
    return order


def _flood_reroute(
    comm: "_Comm", left: int, right: int, seq: int, attempt: int, pair
) -> None:
    """Best-effort broadcast of a dead link to both ring neighbours.

    Each rank that learns of the failure forwards before restarting, so the
    signal chains rightward around the ring (every rank's blocking recv is
    from its left) and unblocks everyone. Sends are small pickled frames on
    otherwise-healthy sockets; failures (e.g. the dead link itself) are
    swallowed — the flood only needs one surviving direction."""
    msg = ("creroute", seq, attempt, (min(pair), max(pair)))
    for nb in {left, right}:
        if nb == comm.rank:
            continue
        try:
            comm.send_to(nb, msg)
        except Exception:  # noqa: BLE001 - best-effort by design
            pass


def _drain_stale_frames(
    comm: "_Comm", skip_peer: int, seq: int, attempt: int,
    quiet_s: float = 0.05,
) -> None:
    """Best-effort sweep of every peer socket (except the new left, whose
    stale frames the hop recv loop handles in-line) at the start of a
    re-routed attempt. The aborted attempt may have left one hop's frames
    queued on a socket the new ring never reads — and a peer's sendall can
    be blocked mid-frame on it, so draining here is also what unblocks that
    peer's collective writer. A current-attempt re-route signal found while
    draining propagates as _LinkFailure."""
    for peer in sorted(comm.peers):
        if peer == skip_peer or peer == comm.rank:
            continue
        sock = comm.peers[peer]
        try:
            old = sock.gettimeout()
        except OSError:
            continue
        try:
            while True:
                sock.settimeout(quiet_s)
                try:
                    hdr = comm.recv_from(peer)
                except OSError:
                    break  # quiet (or dead) socket — nothing to drain
                if not (isinstance(hdr, tuple) and len(hdr) == 4):
                    raise RuntimeError(
                        f"compressed ring desync draining rank {peer}: "
                        f"{hdr!r}"
                    )
                tag, h_seq, h_attempt, rest = hdr
                stale = h_seq < seq or (
                    h_seq == seq and h_attempt < attempt
                )
                if tag == "cseg" and stale:
                    # body frames follow; read them under the op timeout
                    sock.settimeout(old)
                    comm.recv_raw_discard(peer)
                    comm.recv_raw_discard(peer)
                    continue
                if tag == "creroute":
                    if stale:
                        continue
                    raise _LinkFailure(*rest)
                raise RuntimeError(
                    f"compressed ring desync draining rank {peer}: "
                    f"tag={tag!r} seq={h_seq} attempt={h_attempt}"
                )
        finally:
            try:
                sock.settimeout(old)
            except OSError:
                pass


def _recv_compressed_hop(
    comm: "_Comm", left: int, seq: int, attempt: int, hop: int,
    out_q: np.ndarray, out_s: np.ndarray,
) -> None:
    """Receive one compressed-ring hop (header + payload + scales frames),
    draining stale frames from aborted attempts / earlier collectives and
    converting re-route signals into _LinkFailure."""
    while True:
        hdr = comm.recv_from(left)
        if not (isinstance(hdr, tuple) and len(hdr) == 4):
            raise RuntimeError(
                f"unexpected frame on compressed ring: {hdr!r}"
            )
        tag, h_seq, h_attempt, rest = hdr
        stale = h_seq < seq or (h_seq == seq and h_attempt < attempt)
        if tag == "cseg":
            if stale:
                # the aborted attempt's segment bytes follow the header;
                # drain both frames or they corrupt this attempt's stream
                comm.recv_raw_discard(left)
                comm.recv_raw_discard(left)
                continue
            if h_seq != seq or h_attempt != attempt or rest != hop:
                raise RuntimeError(
                    "compressed ring desync: got "
                    f"seq={h_seq} attempt={h_attempt} hop={rest}, expected "
                    f"seq={seq} attempt={attempt} hop={hop}"
                )
            comm.recv_raw_into(left, out_q)
            comm.recv_raw_into(left, out_s)
            return
        if tag == "creroute":
            if stale:
                continue  # duplicate from an already-handled flood
            raise _LinkFailure(*rest)
        raise RuntimeError(f"unexpected compressed ring tag {tag!r}")


def _compressed_ring_pass(
    comm: "_Comm",
    wire,
    quantize,
    dequantize,
    Q: np.ndarray,
    S: np.ndarray,
    rows: int,
    seg_rows: int,
    op: ReduceOp,
    order: List[int],
    seq: int,
    attempt: int,
):
    """One attempt of the compressed ring over ``order``.

    Reduce-scatter hops carry compressed segments; each hop dequantizes the
    incoming segment, accumulates in f32, and requantizes the accumulated
    segment for the next hop (hop 0 forwards the original codes — no extra
    rounding). The allgather phase circulates the reduced compressed
    segments verbatim. Restart-safe: all state derives from the immutable
    (Q, S) input codes, so a _LinkFailure anywhere re-runs cleanly."""
    world = len(order)
    pos = order.index(comm.rank)
    right = order[(pos + 1) % world]
    left = order[(pos - 1) % world]
    row = int(wire.row)
    seg_elems = seg_rows * row

    if attempt > 0:
        _drain_stale_frames(comm, left, seq, attempt)

    # f32 working accumulation, one slab per chunk (chunk j = rows
    # [j*seg_rows, (j+1)*seg_rows) of the padded code matrix). Slabs are
    # decoded lazily at their first accumulate — the chunk this rank sends
    # at hop 0 leaves as the original codes and never needs an f32 copy
    acc = np.empty((world, seg_elems), np.float32)

    def _own_slab(j: int) -> np.ndarray:
        return dequantize(
            Q[j * seg_rows:(j + 1) * seg_rows],
            S[j * seg_rows:(j + 1) * seg_rows],
            seg_elems,
            np.float32,
        )
    recv_q = np.empty((seg_rows, row), np.uint8)
    recv_s = np.empty(seg_rows, np.float32)
    hop = 0

    def _send_recv(send_q: np.ndarray, send_s: np.ndarray) -> None:
        nonlocal hop
        this_hop = hop
        try:
            comm.check_link_fault(comm.rank, right, this_hop)
        except ConnectionError as e:
            _flood_reroute(comm, left, right, seq, attempt,
                           (comm.rank, right))
            raise _LinkFailure(comm.rank, right) from e
        try:
            comm.check_link_fault(left, comm.rank, this_hop)
        except ConnectionError as e:
            _flood_reroute(comm, left, right, seq, attempt,
                           (left, comm.rank))
            raise _LinkFailure(left, comm.rank) from e
        hdr = ("cseg", seq, attempt, this_hop)

        def _writes() -> None:
            comm.send_to(right, hdr)
            comm.send_raw(right, send_q)
            comm.send_raw(right, send_s)

        done, err = comm.submit_write(_writes)
        try:
            _recv_compressed_hop(
                comm, left, seq, attempt, this_hop, recv_q, recv_s
            )
        except _LinkFailure as lf:
            # forward the flood before restarting so the signal keeps
            # chaining rightward past us
            _flood_reroute(comm, left, right, seq, attempt, lf.pair)
            raise
        except (ConnectionError, OSError, ValueError) as e:
            _flood_reroute(comm, left, right, seq, attempt,
                           (left, comm.rank))
            raise _LinkFailure(left, comm.rank) from e
        finally:
            done.wait()
        if err:
            e = err[0]
            _flood_reroute(comm, left, right, seq, attempt,
                           (comm.rank, right))
            raise _LinkFailure(comm.rank, right) from e
        hop += 1

    # reduce-scatter: after world-1 hops this rank holds the fully reduced
    # chunk (pos+1) % world in f32
    for step in range(world - 1):
        s_idx = (pos - step) % world
        r_idx = (pos - step - 1) % world
        if step == 0:
            sq = Q[s_idx * seg_rows:(s_idx + 1) * seg_rows]
            ss = S[s_idx * seg_rows:(s_idx + 1) * seg_rows]
        else:
            sq, ss, _ = quantize(acc[s_idx], row=row)
            ss = np.ascontiguousarray(ss, dtype=np.float32)
        _send_recv(sq, ss)
        # each r_idx is distinct across the sweep, so first touch decodes
        # this rank's own contribution and the hop's payload lands on top
        acc[r_idx] = _own_slab(r_idx)
        acc[r_idx] += dequantize(recv_q, recv_s, seg_elems, np.float32)

    own = (pos + 1) % world
    if op == ReduceOp.AVG:
        acc[own] /= world
    q_own, s_own, _ = quantize(acc[own], row=row)

    Qr = np.empty((world, seg_rows, row), np.uint8)
    Sr = np.empty((world, seg_rows), np.float32)
    Qr[own] = q_own
    Sr[own] = np.ascontiguousarray(s_own, dtype=np.float32)

    # allgather: circulate the reduced compressed segments verbatim
    for step in range(world - 1):
        s_idx = (pos + 1 - step) % world
        r_idx = (pos - step) % world
        _send_recv(Qr[s_idx], Sr[s_idx])
        Qr[r_idx] = recv_q
        Sr[r_idx] = recv_s

    from torchft_tpu.ops.quantization import CompressedWire

    return CompressedWire(
        mode=wire.mode,
        payload=Qr.reshape(world * seg_rows, row)[:rows].copy(),
        scales=Sr.reshape(-1)[:rows].copy(),
        n=wire.n,
        dtype=wire.dtype,
        row=row,
    )


def _compressed_chain_pass(
    comm: "_Comm",
    wire,
    quantize,
    dequantize,
    Q: np.ndarray,
    S: np.ndarray,
    rows: int,
    op: ReduceOp,
    order: List[int],
    seq: int,
    attempt: int,
):
    """Degraded open-chain attempt used when the dead-link set leaves no
    ring but still admits a Hamiltonian path. The reduce sweeps head→tail
    (each hop dequantizes, accumulates in f32, requantizes the full
    buffer), the tail finishes the op (AVG divide) and the reduced codes
    ride back tail→head verbatim. Each rank moves 2 full-buffer hops of
    wire instead of the ring's 2×(1/world) segments — correctness over
    bandwidth, which is the right trade for a re-routed slow step.

    Hop labels are global chain positions (reduce hop i = order[i]→
    order[i+1], broadcast hop (w-1)+(w-1-i) = order[i+1]→order[i]) so both
    endpoints of a hop agree without per-rank counters."""
    world = len(order)
    pos = order.index(comm.rank)
    # comm.rank as a sentinel "no neighbour": _flood_reroute skips self
    left = order[pos - 1] if pos > 0 else comm.rank
    right = order[pos + 1] if pos < world - 1 else comm.rank
    row = int(wire.row)
    pad_rows = Q.shape[0]

    if attempt > 0:
        _drain_stale_frames(comm, left if pos > 0 else right, seq, attempt)

    recv_q = np.empty((pad_rows, row), np.uint8)
    recv_s = np.empty(pad_rows, np.float32)

    def _checked(a: int, b: int, hop: int) -> None:
        try:
            comm.check_link_fault(a, b, hop)
        except ConnectionError as e:
            _flood_reroute(comm, left, right, seq, attempt, (a, b))
            raise _LinkFailure(a, b) from e

    def _send(peer: int, hop: int, sq: np.ndarray, ss: np.ndarray) -> None:
        _checked(comm.rank, peer, hop)
        hdr = ("cseg", seq, attempt, hop)

        def _writes() -> None:
            comm.send_to(peer, hdr)
            comm.send_raw(peer, sq)
            comm.send_raw(peer, ss)

        done, err = comm.submit_write(_writes)
        done.wait()
        if err:
            _flood_reroute(comm, left, right, seq, attempt,
                           (comm.rank, peer))
            raise _LinkFailure(comm.rank, peer) from err[0]

    def _recv(peer: int, hop: int) -> None:
        _checked(peer, comm.rank, hop)
        try:
            _recv_compressed_hop(
                comm, peer, seq, attempt, hop, recv_q, recv_s
            )
        except _LinkFailure as lf:
            _flood_reroute(comm, left, right, seq, attempt, lf.pair)
            raise
        except (ConnectionError, OSError, ValueError) as e:
            _flood_reroute(comm, left, right, seq, attempt,
                           (peer, comm.rank))
            raise _LinkFailure(peer, comm.rank) from e

    # reduce sweep head → tail
    acc = None
    if pos > 0:
        _recv(left, pos - 1)
        acc = dequantize(Q, S, Q.size, np.float32)
        acc += dequantize(recv_q, recv_s, Q.size, np.float32)
    if pos < world - 1:
        if acc is None:  # chain head forwards its original codes unrounded
            sq, ss = Q, S
        else:
            sq, ss, _ = quantize(acc, row=row)
            ss = np.ascontiguousarray(ss, dtype=np.float32)
        _send(right, pos, sq, ss)
        # broadcast sweep tail → head
        _recv(right, (world - 1) + (world - 1 - pos))
        out_q = recv_q.copy()
        out_s = recv_s.copy()
    else:
        if op == ReduceOp.AVG:
            acc /= world
        oq, os_, _ = quantize(acc, row=row)
        out_q = np.asarray(oq)
        out_s = np.ascontiguousarray(os_, dtype=np.float32)
    if pos > 0:
        _send(left, (world - 1) + (world - 1 - (pos - 1)), out_q, out_s)

    from torchft_tpu.ops.quantization import CompressedWire

    return CompressedWire(
        mode=wire.mode,
        payload=out_q.reshape(pad_rows, row)[:rows].copy(),
        scales=out_s.reshape(-1)[:rows].copy(),
        n=wire.n,
        dtype=wire.dtype,
        row=row,
    )


def _ring_allreduce_compressed(
    comm: "_Comm",
    wire,
    op: ReduceOp,
    timeout: float = 60.0,
    on_reroute=None,
):
    """Compressed ring allreduce with mid-collective link failover.

    The FT layer lives *inside* the collective (R2CCL, PAPERS.md): a hop
    failure — socket error or injected ``link_faults`` entry — floods a
    re-route signal around the ring, every rank restarts under the shared
    ``retry.py`` policy (TORCHFT_RETRY_*), and the ring re-forms over a
    deterministic ordering that avoids every known-dead link. The step
    finishes as a re-routed slow step instead of a swallowed one.
    ``on_reroute(pair, attempt)`` fires once per re-route on the rank(s)
    that initiated or learned of it, before the restart."""
    from torchft_tpu.ops.quantization import codec
    from torchft_tpu.retry import RetryPolicy, retry_call

    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(
            f"compressed allreduce supports SUM and AVG, got {op}"
        )
    quantize, dequantize = codec(wire.mode)
    world = comm.world
    seq = comm.cring_seq
    comm.cring_seq = seq + 1

    scales = np.asarray(wire.scales, dtype=np.float32).reshape(-1)
    rows = int(scales.size)
    row = int(wire.row)
    seg_rows = max(1, -(-rows // world))
    pad_rows = seg_rows * world
    Q = np.zeros((pad_rows, row), np.uint8)
    Q[:rows] = np.asarray(wire.payload).reshape(rows, row)
    S = np.ones(pad_rows, np.float32)
    S[:rows] = scales

    # seed from the comm's known-dead set: once a link has killed one
    # collective, later collectives on this generation route around it from
    # attempt 0 instead of re-discovering the failure every step
    dead: set = set(comm.cring_dead)
    state = {"attempt": 0}

    def _attempt(_remaining: float):
        order = _ring_order(world, dead)
        chain = None
        if order is None:
            # no surviving cycle — fall back to an open chain (any single
            # dead link at world<=3 lands here: a 3-cycle needs all edges)
            chain = _chain_order(world, dead)
            if chain is None:
                raise RuntimeError(
                    f"compressed ring cannot re-form at world={world}: "
                    f"dead links "
                    f"{sorted(tuple(sorted(d)) for d in dead)} leave no "
                    "valid ring or chain ordering"
                )
        try:
            if order is not None:
                return _compressed_ring_pass(
                    comm, wire, quantize, dequantize, Q, S, rows, seg_rows,
                    op, order, seq, state["attempt"],
                )
            return _compressed_chain_pass(
                comm, wire, quantize, dequantize, Q, S, rows,
                op, chain, seq, state["attempt"],
            )
        except _LinkFailure as lf:
            dead.add(frozenset(lf.pair))
            comm.cring_dead.add(frozenset(lf.pair))
            state["attempt"] += 1
            if on_reroute is not None:
                try:
                    on_reroute(lf.pair, state["attempt"])
                except Exception:  # noqa: BLE001 - observer must not kill op
                    pass
            raise

    return retry_call(
        _attempt,
        RetryPolicy.from_env(),
        timeout=timeout,
        retryable=(_LinkFailure,),
    )


class ProcessGroupHost(ProcessGroup):
    """CPU collectives over a TCP full mesh between replica groups.

    The Gloo-equivalent data plane (reference ProcessGroupGloo,
    process_group.py:643-711): used for the fault-tolerant replicated-dim
    traffic, tests, and control data. JAX arrays are staged through host
    memory; outputs are plain numpy (callers ``device_put`` as needed).

    Collectives are dispatched on a single background thread (preserving
    issue order, like a communication stream); each op arms an abort watchdog
    for ``timeout`` seconds (reference abort-based recovery,
    process_group.py:739-763).
    """

    class _Generation:
        """One configure() generation: its mesh, dispatch queue, and error
        state. Ops are bound to the generation they were submitted under, so
        a late failure from a torn-down mesh can never poison (or abort) the
        fresh one."""

        def __init__(self, comm: "_Comm") -> None:
            self.comm = comm
            self.queue: queue.Queue = queue.Queue()
            self.error: Optional[Exception] = None
            # "p2p" | "collective" | None — fixed by the first op. p2p
            # sends ride per-peer writer threads while collectives write
            # from the dispatch/ring threads; mixing the two on one
            # generation could reorder frames on a shared socket, so it is
            # rejected (in-tree usage already splits them: the Manager's PG
            # does collectives, the recovery PGTransport's PG does p2p).
            self.mode: Optional[str] = None
            self.mode_lock = threading.Lock()

        def claim_mode(self, mode: str) -> None:
            with self.mode_lock:
                if self.mode is None:
                    self.mode = mode
                elif self.mode != mode:
                    raise RuntimeError(
                        f"ProcessGroupHost generation already used for "
                        f"{self.mode} ops; p2p and collective ops cannot "
                        "mix on one generation (frame ordering) — use a "
                        "separate PG (the reference uses a dedicated "
                        "recovery PG for checkpoints too)"
                    )

        def abort(self) -> None:
            if self.error is None:
                self.error = RuntimeError("process group aborted")
            self.comm.abort()

    def __init__(self, timeout: "float | timedelta" = 60.0) -> None:
        super().__init__()
        self.set_timeout(timeout)
        self._gen: Optional[ProcessGroupHost._Generation] = None
        self._rank = 0
        self._world = 1
        self._lock = threading.Lock()
        # injected link faults (tests / chaos): shared by reference with
        # every generation's _Comm so arming works before or after configure
        self._link_faults: Dict[frozenset, int] = {}
        self._reroute_observer: Optional[Callable[[tuple, int], None]] = None
        # wire counters folded in from retired generations so wire_stats()
        # stays monotonic across reconfigures
        self._wire_totals = {"bytes_sent": 0, "bytes_recv": 0, "busy_s": 0.0}

    # -- fault injection & failover observability -------------------------
    def inject_link_fault(self, src: int, dst: int, at_hop: int = 0) -> None:
        """Sever ring link (src, dst) from hop ``at_hop`` of every
        compressed collective on this PG — the network-fault analog of
        FakeProcessGroupWrapper.report_future_error, but *inside* the
        collective so the ring's re-route path is what recovers. The link
        stays dead until :meth:`clear_link_faults`."""
        self._link_faults[frozenset((int(src), int(dst)))] = int(at_hop)

    def clear_link_faults(self) -> None:
        self._link_faults.clear()

    def set_reroute_observer(self, fn) -> None:
        """``fn(dead_pair, attempt)`` fires on every mid-collective
        re-route (Manager wires this into the ``collective_reroute``
        counter and a flight-recorder breadcrumb)."""
        self._reroute_observer = fn

    def wire_stats(self) -> Dict[str, float]:
        """Cumulative transport counters across every generation this PG
        has run: frame bytes sent/received and ``wire_busy_s`` — seconds
        the sender spent inside sendall actually pushing those bytes
        (receive waits excluded; see _Comm.wire_busy_s).
        ``bytes_sent / wire_busy_s`` is the delivered wire bandwidth the
        compressed-allreduce bench compares across compress modes."""
        with self._lock:
            out = dict(self._wire_totals)
            gen = self._gen
        if gen is not None:
            out["bytes_sent"] += gen.comm.bytes_sent
            out["bytes_recv"] += gen.comm.bytes_recv
            out["busy_s"] += gen.comm.wire_busy_s
        return out

    # -- lifecycle --------------------------------------------------------
    def configure(self, store_addr, replica_rank, replica_world_size, quorum_id=0):
        comm = _Comm(
            rank=replica_rank,
            world=replica_world_size,
            store_addr=store_addr,
            quorum_id=quorum_id,
            timeout=self._timeout,
        )
        # share (not copy) the fault registry: arming after configure must
        # reach the live generation
        comm.link_faults = self._link_faults
        gen = ProcessGroupHost._Generation(comm)
        with self._lock:
            old, self._gen = self._gen, gen
            self._rank = replica_rank
            self._world = replica_world_size
            if old is not None:
                self._wire_totals["bytes_sent"] += old.comm.bytes_sent
                self._wire_totals["bytes_recv"] += old.comm.bytes_recv
                self._wire_totals["busy_s"] += old.comm.wire_busy_s
        if old is not None:
            old.abort()
            old.queue.put(None)
        threading.Thread(
            target=self._dispatch_loop,
            args=(gen,),
            daemon=True,
            name=f"pg_host_dispatch_r{replica_rank}",
        ).start()

    def set_timeout(self, timeout) -> None:
        super().set_timeout(timeout)
        # reaches the wire: without this only the abort watchdog moves and
        # the sockets keep their configure-time timeouts (asymmetric
        # failures: dialed sockets time out, accepted ones never would).
        # Guarded: the constructor calls set_timeout before _lock exists.
        lock = getattr(self, "_lock", None)
        if lock is None:
            return
        with lock:
            gen = self._gen
        if gen is not None:
            gen.comm.settimeout(self._timeout)

    def abort(self) -> None:
        with self._lock:
            gen = self._gen
        if gen is not None:
            gen.abort()
            from torchft_tpu.observability import log_error_event

            log_error_event(
                source="process_group",
                event="abort",
                replica_rank=self._rank,
                replica_world_size=self._world,
            )
            # abort-triggered postmortem dump (reference: abort→FR named-pipe
            # trigger, process_group.py:875-883)
            _fr.recorder.record("pg_abort", rank=self._rank, world=self._world)
            _fr.recorder.dump(reason="pg_abort")

    def shutdown(self) -> None:
        with self._lock:
            gen, self._gen = self._gen, None
        if gen is not None:
            gen.abort()
            gen.queue.put(None)

    def errored(self) -> Optional[Exception]:
        with self._lock:
            return self._gen.error if self._gen is not None else None

    def size(self) -> int:
        return self._world

    def rank(self) -> int:
        return self._rank

    # -- dispatch ---------------------------------------------------------
    def _dispatch_loop(self, gen: "ProcessGroupHost._Generation") -> None:
        while True:
            item = gen.queue.get()
            if item is None:
                return
            fn, fut = item
            try:
                # the watchdog aborts THIS generation's mesh only
                with context_timeout(gen.abort, self._timeout):
                    result = fn(gen.comm)
            except BaseException as e:  # noqa: BLE001
                gen.error = e if isinstance(e, Exception) else RuntimeError(str(e))
                try:
                    fut.set_exception(e)
                except RuntimeError:
                    pass
            else:
                # set_result runs chained done-callbacks synchronously;
                # they must not be charged against the collective's
                # watchdog (a slow callback would abort a healthy mesh)
                try:
                    fut.set_result(result)
                except RuntimeError:
                    pass

    def _submit(self, fn: Callable[["_Comm"], Any], name: str = "op",
                mode: str = "collective") -> Work:
        _fr.recorder.record(
            "collective", op=name, rank=self._rank, world=self._world
        )
        with self._lock:
            gen = self._gen
            if gen is None:
                raise RuntimeError("process group is not configured")
            if gen.error is not None:
                raise gen.error
            gen.claim_mode(mode)
            fut: Future[Any] = Future()
            gen.queue.put((fn, fut))
            return FutureWork(fut)

    # -- collectives ------------------------------------------------------
    def allreduce(self, arrays, op=ReduceOp.SUM):
        from torchft_tpu.ops.quantization import CompressedWire

        host = [_to_host(a) for a in arrays]

        def _run(comm):
            # compressed buckets always ride the self-healing ring: it is
            # the only path whose reduce step can dequantize→accumulate→
            # requantize per hop, and the only one that can re-route around
            # a dead link mid-collective
            if len(host) == 1 and isinstance(host[0], CompressedWire):
                wire = host[0]
                if comm.world == 1:
                    return [
                        CompressedWire(
                            wire.mode, wire.payload.copy(),
                            wire.scales.copy(), wire.n, wire.dtype,
                            wire.row,
                        )
                    ]
                return [
                    _ring_allreduce_compressed(
                        comm, wire, op, timeout=self._timeout,
                        on_reroute=self._reroute_observer,
                    )
                ]
            if comm.world == 1:
                # independent copies: at world >= 2 results never alias the
                # inputs (the ring/exchange paths allocate), and the
                # degraded single-replica fleet must honor the same
                # contract. _copy_payload is tuple-safe (quantized wire).
                return [_copy_payload(h) for h in host]
            # Large ndarray payloads ride the ring (per-rank traffic ~2x
            # payload, world-size-independent); small or non-ndarray ones
            # (quantized tuples) use the one-round full-mesh exchange.
            if all(isinstance(h, np.ndarray) for h in host) and (
                sum(h.nbytes for h in host) >= _RING_MIN_BYTES
            ):
                return _ring_allreduce(comm, host, op)
            payload = {r: host for r in range(comm.world) if r != comm.rank}
            gathered = comm.exchange({**payload, comm.rank: host})
            return [
                _reduce_np(op, [gathered[r][i] for r in range(comm.world)])
                for i in range(len(host))
            ]

        return self._submit(_run, "allreduce")

    def allgather(self, arrays):
        host = [_to_host(a) for a in arrays]

        def _run(comm):
            if comm.world == 1:
                return [[_copy_payload(h) for h in host]]
            gathered = comm.exchange(
                {r: host for r in range(comm.world)}
            )
            return [gathered[r] for r in range(comm.world)]

        return self._submit(_run, "allgather")

    def broadcast(self, arrays, root=0):
        host = [_to_host(a) for a in arrays]

        def _run(comm):
            if comm.world == 1:
                return [_copy_payload(h) for h in host]
            if comm.rank == root:
                for peer in range(comm.world):
                    if peer != comm.rank:
                        comm.send_to(peer, host)
                # ack round-trip makes broadcast a real collective: a small
                # payload to a dead peer can land in the kernel buffer and
                # "succeed", leaving the root blind to the failure — NCCL-
                # class broadcasts are communicator-wide and error on a dead
                # rank, and the resiliency matrix relies on that contract
                for peer in range(comm.world):
                    if peer != comm.rank:
                        ack = comm.recv_from(peer)
                        if ack != ("bcast_ack", peer):
                            raise RuntimeError(f"bad broadcast ack: {ack!r}")
                return host
            out = comm.recv_from(root)
            comm.send_to(root, ("bcast_ack", comm.rank))
            return out

        return self._submit(_run, "broadcast")

    def reduce_scatter(self, input_chunks, op=ReduceOp.SUM):
        host = [[_to_host(a) for a in chunk] for chunk in input_chunks]

        def _run(comm):
            if comm.world == 1:
                return [_copy_payload(h) for h in host[0]]
            assert len(host) == comm.world, "need one chunk per rank"
            gathered = comm.exchange({r: host[r] for r in range(comm.world)})
            mine = [gathered[r] for r in range(comm.world)]
            return [
                _reduce_np(op, [mine[r][i] for r in range(comm.world)])
                for i in range(len(host[0]))
            ]

        return self._submit(_run, "reduce_scatter")

    def alltoall(self, input_chunks):
        host = [_to_host(a) for a in input_chunks]

        def _run(comm):
            if comm.world == 1:
                return [_copy_payload(h) for h in host]
            assert len(host) == comm.world, "need one chunk per rank"
            gathered = comm.exchange({r: host[r] for r in range(comm.world)})
            return [gathered[r] for r in range(comm.world)]

        return self._submit(_run, "alltoall")

    def send(self, arrays, dst, tag=0):
        host = [_to_host(a) for a in arrays]
        _fr.recorder.record(
            "collective", op="send", rank=self._rank, world=self._world
        )
        with self._lock:
            gen = self._gen
            if gen is None:
                raise RuntimeError("process group is not configured")
            if gen.error is not None:
                raise gen.error
            gen.claim_mode("p2p")
        fut: Future[Any] = Future()
        timeout = self._timeout

        def job() -> None:
            # own watchdog: the job runs on the per-peer writer thread, not
            # the dispatch thread (see _Comm.p2p_send_async — symmetric
            # send/send would deadlock both dispatch threads otherwise)
            with context_timeout(gen.abort, timeout):
                comm = gen.comm
                if all(isinstance(h, np.ndarray) for h in host) and (
                    sum(h.nbytes for h in host) >= _RING_MIN_BYTES
                ):
                    # raw-frame p2p: a small pickled header with dtype/shape
                    # metas, then each leaf's bytes straight from memory —
                    # no pickling copy of multi-GB checkpoint leaves
                    metas = [(str(h.dtype), h.shape) for h in host]
                    comm.send_to(dst, ("p2p_raw", tag, metas))
                    for h in host:
                        comm.send_raw(dst, np.ascontiguousarray(h))
                else:
                    comm.send_to(dst, ("p2p", tag, host))

        def fail(e: Exception) -> None:
            gen.error = gen.error or e

        gen.comm.p2p_send_async(dst, job, fut, fail)
        return FutureWork(fut)

    def recv(self, src, tag=0):
        return self.recv_into([], src, tag)

    def recv_into(self, buffers, src, tag=0):
        """Like :meth:`recv` (which delegates here with no buffers), but
        raw-frame payloads land DIRECTLY in the caller's preallocated
        ``buffers`` — no wire allocation and no copy (the in-place
        checkpoint receive's hot path; beyond the torch PG surface, so
        transports feature-detect it with ``getattr``).

        The returned Work's value is the list of received arrays: entry i
        IS ``buffers[i]`` when the wire used a raw frame and the buffer
        can absorb it (the shared ``can_absorb`` predicate, contiguity
        required); otherwise a freshly allocated array (small pickled
        messages, mismatched buffers, or more leaves than buffers).
        """
        def _run(comm):
            kind, got_tag, payload = comm.recv_from(src)
            assert got_tag == tag, (kind, got_tag, tag)
            if kind == "p2p":
                return payload  # pickled small-message path: no raw frames
            assert kind == "p2p_raw", kind
            # one absorb predicate across every in-place path (no import
            # cycle: _serialization depends only on numpy/utils)
            from torchft_tpu.checkpointing._serialization import can_absorb
            from torchft_tpu.utils import np_dtype_from_str

            out = []
            for i, (dtype_str, shape) in enumerate(payload):
                target = buffers[i] if i < len(buffers) else None
                if not can_absorb(target, shape, dtype_str,
                                  require_contiguous=True):
                    target = np.empty(shape, np_dtype_from_str(dtype_str))
                comm.recv_raw_into(src, target)
                out.append(target)
            return out

        return self._submit(_run, "recv", mode="p2p")


# ---------------------------------------------------------------------------
# Subprocess-isolated ("Baby") process groups
# ---------------------------------------------------------------------------


def _call_quietly(fn: Any) -> None:
    try:
        fn()
    except Exception:  # noqa: BLE001 - best-effort abort path
        pass


def _baby_worker(
    pg_class: type,
    store_addr: str,
    rank: int,
    world: int,
    quorum_id: int,
    timeout: float,
    req_conn: Any,
    fut_conn: Any,
    abort_cell: Optional[list] = None,
) -> None:
    """Child-side loop of a Baby process group.

    Runs the real PG inside the child (reference `_worker`,
    process_group.py:1565-1695): configures it, then serves
    ``("func", op_id, name, args, kwargs)`` requests from the parent, posting
    each op's result or exception to the future pipe as it completes. Module
    top-level so the spawn start method can pickle it.
    """
    fut_lock = threading.Lock()

    def _post(op_id: Any, payload: Any, kind: str) -> None:
        with fut_lock:
            try:
                fut_conn.send((op_id, kind, payload))
            except (OSError, EOFError, BrokenPipeError):
                pass  # parent is gone; the loop will exit on the next recv
            except Exception as e:  # noqa: BLE001 - e.g. unpicklable payload
                # Never lose the op: degrade to a picklable error so the
                # parent future resolves instead of hanging to timeout.
                try:
                    fut_conn.send(
                        (op_id, "exception",
                         RuntimeError(f"baby worker could not ship {kind}: {e!r}"))
                    )
                except (OSError, EOFError, BrokenPipeError):
                    pass

    try:
        pg = pg_class(timeout=timeout)
        pg.configure(store_addr, rank, world, quorum_id=quorum_id)
    except Exception as e:  # noqa: BLE001
        _post("init", e, "exception")
        return
    if abort_cell is not None:
        # Parent-side abort hook. Only effective with the thread-backed
        # DummyContext (shared memory): kill() is a no-op for threads and
        # closing the request pipe only unblocks this recv loop, not an op
        # wedged inside the inner PG — the hook lets the parent's abort()
        # reach pg.abort() directly. Under a spawn context this appends to
        # the child's pickled copy, which the parent never sees (and never
        # needs: kill() works there).
        abort_cell.append(pg.abort)
    _post("init", None, "result")

    while True:
        try:
            cmd = req_conn.recv()
        except (EOFError, OSError):
            break
        if cmd is None:
            break
        if cmd[0] == "func":
            _, op_id, name, args, kwargs = cmd
            try:
                work = getattr(pg, name)(*args, **kwargs)
            except Exception as e:  # noqa: BLE001
                _post(op_id, e, "exception")
                continue

            def _done(f: Future, op_id: Any = op_id) -> None:
                exc = f.exception()
                if exc is not None:
                    if not isinstance(exc, Exception):
                        exc = RuntimeError(str(exc))
                    _post(op_id, exc, "exception")
                else:
                    _post(op_id, f.value(), "result")

            work.get_future().add_done_callback(_done)
    pg.shutdown()


class ProcessGroupBaby(ProcessGroup):
    """Runs the real PG in a spawned child process so a hung or wedged
    communicator can be killed without killing the trainer.

    Reference: ProcessGroupBaby, process_group.py:1445-1923. On TPU this
    isolation matters doubly: the trainer process owns the (expensive,
    stateful) JAX/TPU runtime, so a stuck DCN socket or host collective must
    never require restarting it. Arrays cross the pipe as numpy — the host
    staging the cross-replica-group plane already requires — rather than the
    reference's shared-memory tensors.

    ``ctx`` defaults to the ``spawn`` multiprocessing context; pass
    :class:`torchft_tpu.multiprocessing_dummy_context.DummyContext` to run the
    child threaded in-process (reference multiprocessing_dummy_context
    pattern, used by the fast test matrix).
    """

    PG_CLASS: type = None  # type: ignore[assignment]  # set by subclasses

    class _Gen:
        """One configure() generation: child process, pipes, outstanding ops."""

        def __init__(
            self,
            proc: Any,
            req: "_MonitoredPipe",
            fut: "_MonitoredPipe",
            abort_cell: Optional[list] = None,
        ):
            self.proc = proc
            self.req = req
            self.fut_pipe = fut
            self.futures: Dict[int, Future] = {}
            self.lock = threading.Lock()
            self.error: Optional[Exception] = None
            self.stopped = False
            # child-side pg.abort hook; populated only under DummyContext
            self.abort_cell: list = [] if abort_cell is None else abort_cell

    def __init__(self, timeout: "float | timedelta" = 60.0, ctx: Any = None) -> None:
        super().__init__()
        self.set_timeout(timeout)
        self._ctx = ctx
        self._gen: Optional[ProcessGroupBaby._Gen] = None
        self._rank = 0
        self._world = 1
        self._next_op_id = 0
        self._lock = threading.Lock()

    # -- lifecycle --------------------------------------------------------
    def configure(self, store_addr, replica_rank, replica_world_size, quorum_id=0):
        from torchft_tpu.multiprocessing import _MonitoredPipe

        self._teardown(terminal=False)

        if self._ctx is None:
            import multiprocessing as mp

            ctx = mp.get_context("spawn")
        else:
            ctx = self._ctx
        req_local, req_remote = ctx.Pipe()
        fut_local, fut_remote = ctx.Pipe()
        abort_cell: list = []
        proc = ctx.Process(
            target=_baby_worker,
            args=(
                type(self).PG_CLASS,
                store_addr,
                replica_rank,
                replica_world_size,
                quorum_id,
                self._timeout,
                req_remote,
                fut_remote,
                abort_cell,
            ),
            daemon=True,
            name=f"baby_pg_r{replica_rank}",
        )
        proc.start()
        # With real mp Connections, drop the parent's copies of the child ends
        # so a dead child reads as EOF on the local ends. (The dummy context's
        # close() signals the peer instead, so leave those open.)
        import multiprocessing.connection as _mpc

        for remote in (req_remote, fut_remote):
            if isinstance(remote, _mpc.Connection):
                remote.close()

        gen = ProcessGroupBaby._Gen(
            proc, _MonitoredPipe(req_local), _MonitoredPipe(fut_local), abort_cell
        )
        # Init ack: the child's configure() rendezvouses with its peers, so
        # give it the full op timeout plus slack for process startup. On any
        # failure (timeout, child init error) reap the child and pipes — a
        # trainer reconfigures every quorum, so a failed configure must not
        # orphan a live child holding sockets and KV entries.
        try:
            op_id, kind, payload = gen.fut_pipe.recv(self._timeout + 30.0)  # type: ignore[misc]
            assert op_id == "init", op_id
            if kind == "exception":
                raise payload
        except BaseException:
            gen.stopped = True
            gen.req.close()
            gen.fut_pipe.close()
            if hasattr(proc, "kill"):
                proc.kill()
            proc.join(5.0)
            raise

        with self._lock:
            self._gen = gen
            self._rank = replica_rank
            self._world = replica_world_size
        threading.Thread(
            target=self._future_handler,
            args=(gen,),
            daemon=True,
            name=f"baby_pg_futures_r{replica_rank}",
        ).start()

    def _future_handler(self, gen: "ProcessGroupBaby._Gen") -> None:
        """Parent-side pump: resolves parent futures from the future pipe
        (reference `_future_handler`, process_group.py:1697-1730)."""
        while True:
            if gen.stopped:
                return
            try:
                if not gen.fut_pipe.poll(0.1):
                    continue
                op_id, kind, payload = gen.fut_pipe.recv(0)  # type: ignore[misc]
            except TimeoutError:
                continue
            except (EOFError, OSError):
                err = gen.error or RuntimeError("baby process group child died")
                self._fail_gen(gen, err)
                return
            with gen.lock:
                fut = gen.futures.pop(op_id, None)
            if fut is None:
                continue
            try:
                if kind == "exception":
                    gen.error = payload
                    fut.set_exception(payload)
                else:
                    fut.set_result(payload)
            except RuntimeError:
                pass  # future already resolved (e.g. by abort)

    def _fail_gen(self, gen: "ProcessGroupBaby._Gen", err: Exception) -> None:
        gen.error = gen.error or err
        with gen.lock:
            outstanding, gen.futures = dict(gen.futures), {}
        for fut in outstanding.values():
            try:
                fut.set_exception(err)
            except RuntimeError:
                pass

    def _teardown(self, terminal: bool) -> None:
        with self._lock:
            gen, self._gen = self._gen, None
        if gen is None:
            return
        gen.stopped = True
        try:
            gen.req.send(None)  # polite shutdown for thread-backed children
        except (OSError, EOFError, BrokenPipeError):
            pass
        gen.req.close()
        gen.fut_pipe.close()
        if hasattr(gen.proc, "kill"):
            gen.proc.kill()
        gen.proc.join(5.0)
        self._fail_gen(
            gen,
            RuntimeError(
                "process group shut down"
                if terminal
                else "process group torn down for reconfiguration"
            ),
        )

    def abort(self) -> None:
        with self._lock:
            gen = self._gen
        if gen is None:
            return
        gen.error = gen.error or RuntimeError("process group aborted")
        gen.stopped = True
        if hasattr(gen.proc, "kill"):
            gen.proc.kill()
        gen.req.close()
        gen.fut_pipe.close()
        # Under DummyContext the "child" is a thread: kill() was a no-op and
        # closing the pipes only unblocks its recv loop, not an op wedged
        # inside the inner PG. Invoke the child's pg.abort() hook directly —
        # on a daemon thread, because abort() must return promptly even if
        # the inner abort itself wedges.
        for hook in list(gen.abort_cell):
            threading.Thread(
                target=lambda h=hook: _call_quietly(h),
                daemon=True,
                name="baby_pg_inner_abort",
            ).start()
        self._fail_gen(gen, gen.error)
        # Parent-side postmortem: the child (and its inner PG's abort-time
        # dump) was just killed, so the dump must happen here (reference:
        # abort-triggered FR dump, process_group.py:875-883).
        from torchft_tpu.observability import log_error_event

        log_error_event(
            source="baby_process_group",
            event="abort",
            replica_rank=self._rank,
            replica_world_size=self._world,
        )
        _fr.recorder.record("baby_pg_abort", rank=self._rank, world=self._world)
        _fr.recorder.dump(reason="baby_pg_abort")

    def shutdown(self) -> None:
        self._teardown(terminal=True)

    def errored(self) -> Optional[Exception]:
        with self._lock:
            gen = self._gen
        if gen is None:
            return None
        if gen.error is None and not gen.proc.is_alive() and not gen.stopped:
            gen.error = RuntimeError(
                f"baby process group child exited (exitcode={gen.proc.exitcode})"
            )
        return gen.error

    def size(self) -> int:
        return self._world

    def rank(self) -> int:
        return self._rank

    def num_active_work(self) -> int:
        """Outstanding ops not yet resolved (reference introspection,
        process_group.py:1801-1804)."""
        with self._lock:
            gen = self._gen
        if gen is None:
            return 0
        with gen.lock:
            return len(gen.futures)

    # -- dispatch ---------------------------------------------------------
    def _submit(self, name: str, *args: Any, **kwargs: Any) -> Work:
        with self._lock:
            gen = self._gen
            if gen is None:
                raise RuntimeError("process group is not configured")
            if gen.error is not None:
                raise gen.error
            op_id = self._next_op_id
            self._next_op_id += 1
        fut: Future = Future()
        with gen.lock:
            gen.futures[op_id] = fut
        _fr.recorder.record("collective", op=name, rank=self._rank, world=self._world)
        try:
            gen.req.send(("func", op_id, name, list(args), kwargs))
        except (OSError, EOFError, BrokenPipeError) as e:
            err = RuntimeError(f"baby process group pipe broken: {e}")
            self._fail_gen(gen, err)
            raise err from e
        # Close the register/fail race: _fail_gen swaps gen.futures under
        # gen.lock and fails only the swapped-out set, so a future registered
        # after the swap would never resolve (with the thread-backed
        # DummyContext the send above lands silently in an un-drained queue
        # and the caller would hang to its wait timeout). _fail_gen sets
        # gen.error *before* the swap, so if neither stopped nor error is
        # visible here, our future was registered in time and is covered.
        if gen.stopped or gen.error is not None:
            with gen.lock:
                orphan = gen.futures.pop(op_id, None)
            if orphan is not None:
                try:
                    orphan.set_exception(
                        gen.error or RuntimeError("process group stopped")
                    )
                except RuntimeError:
                    pass  # resolved concurrently
        return FutureWork(fut)

    # -- collectives ------------------------------------------------------
    def allreduce(self, arrays, op=ReduceOp.SUM):
        return self._submit("allreduce", [_to_host(a) for a in arrays], op)

    def allgather(self, arrays):
        return self._submit("allgather", [_to_host(a) for a in arrays])

    def broadcast(self, arrays, root=0):
        return self._submit("broadcast", [_to_host(a) for a in arrays], root)

    def reduce_scatter(self, input_chunks, op=ReduceOp.SUM):
        host = [[_to_host(a) for a in chunk] for chunk in input_chunks]
        return self._submit("reduce_scatter", host, op)

    def alltoall(self, input_chunks):
        return self._submit("alltoall", [_to_host(a) for a in input_chunks])

    def send(self, arrays, dst, tag=0):
        return self._submit("send", [_to_host(a) for a in arrays], dst, tag)

    def recv(self, src, tag=0):
        return self._submit("recv", src, tag)


class ProcessGroupBabyHost(ProcessGroupBaby):
    """Baby PG running :class:`ProcessGroupHost` in the child (the reference's
    ProcessGroupBabyGloo, process_group.py:1978-2038)."""

    PG_CLASS = ProcessGroupHost


# ---------------------------------------------------------------------------
# Wrappers
# ---------------------------------------------------------------------------


class _ErrorSwallowingWork(Work):
    """Work whose future errors resolve to a default value instead of raising
    (reference: process_group.py:1137-1173)."""

    def __init__(self, pg: "ErrorSwallowingProcessGroupWrapper", work: Work,
                 default_fn: Callable[[], Any]):
        self._pg = pg
        self._work = work
        self._future: Future[Any] = Future()

        def _transfer(f: Future[Any]) -> None:
            exc = f.exception()
            if exc is not None:
                self._pg.report_error(
                    exc if isinstance(exc, Exception) else RuntimeError(str(exc))
                )
                # default built lazily, only on the error path — and a
                # default_fn that itself raises (e.g. non-addressable
                # sharded arrays) must fail the future, not strand it
                # (Future._invoke swallows callback exceptions)
                try:
                    self._future.set_result(default_fn())
                except Exception as e:  # noqa: BLE001
                    try:
                        self._future.set_exception(e)
                    except RuntimeError:
                        pass
            else:
                self._future.set_result(f.value())

        work.get_future().add_done_callback(_transfer)

    def wait(self, timeout=None):
        self._future.wait(timeout)
        return True

    def get_future(self):
        return self._future


class ErrorSwallowingProcessGroupWrapper(ProcessGroup):
    """Swallows collective errors: after the first error every op returns its
    input unchanged (identity for the train loop) until reconfigured.

    Reference: process_group.py:1176-1249. This is what lets a replica keep
    stepping through a dead communicator — the Manager discards the step at
    should_commit time.
    """

    def __init__(self, pg: ProcessGroup) -> None:
        super().__init__()
        self._pg = pg
        self._error: Optional[Exception] = None

    @property
    def device_native(self) -> bool:
        # forward the inner PG's data-plane capability so wrapping a
        # ProcessGroupXLA doesn't silently re-enable host staging in the
        # Manager (it reads this attribute off the outermost PG)
        return getattr(self._pg, "device_native", False)

    def parent(self) -> ProcessGroup:
        return self._pg

    def error(self) -> Optional[Exception]:
        return self._error

    def report_error(self, e: Exception) -> None:
        self._error = e

    def configure(self, store_addr, replica_rank, replica_world_size, quorum_id=0):
        self._error = None
        self._pg.configure(store_addr, replica_rank, replica_world_size, quorum_id)

    def prepare_configure(
        self, store_addr, replica_rank, replica_world_size, quorum_id=0
    ) -> Optional[Callable[[], None]]:
        # forward the split so wrapping a prepare/commit PG keeps the commit
        # on the main thread; the swallowed-error state clears when the new
        # communicator is actually LIVE (commit time for split PGs)
        inner = self._pg.prepare_configure(
            store_addr, replica_rank, replica_world_size, quorum_id=quorum_id
        )
        if inner is None:
            self._error = None
            return None

        def commit() -> None:
            inner()
            self._error = None

        return commit

    def abort(self) -> None:
        self._pg.abort()

    def shutdown(self) -> None:
        self._pg.shutdown()

    def errored(self) -> Optional[Exception]:
        return self._error or self._pg.errored()

    def size(self) -> int:
        return self._pg.size()

    def rank(self) -> int:
        return self._pg.rank()

    def set_timeout(self, timeout) -> None:
        self._pg.set_timeout(timeout)

    def _guard(self, fn: Callable[[], Work], default_fn: Callable[[], Any]) -> Work:
        """``default_fn`` is LAZY: building a swallow default stages the
        whole payload to host (blocking D2H for device-native trees, and an
        outright error for non-addressable sharded arrays), so it must only
        run on the error path — never per healthy op."""
        if self._error is not None:
            return DummyWork(default_fn())
        try:
            return _ErrorSwallowingWork(self, fn(), default_fn)
        except Exception as e:  # noqa: BLE001
            self.report_error(e)
            return DummyWork(default_fn())

    def allreduce(self, arrays, op=ReduceOp.SUM):
        return self._guard(
            lambda: self._pg.allreduce(arrays, op),
            lambda: [_to_host(a) for a in arrays],
        )

    def allgather(self, arrays):
        # contract: one entry per rank (identity rows for every rank)
        return self._guard(
            lambda: self._pg.allgather(arrays),
            lambda: [
                [_to_host(a) for a in arrays] for _ in range(self._pg.size())
            ],
        )

    def broadcast(self, arrays, root=0):
        return self._guard(
            lambda: self._pg.broadcast(arrays, root),
            lambda: [_to_host(a) for a in arrays],
        )

    def reduce_scatter(self, input_chunks, op=ReduceOp.SUM):
        # identity default = the chunk THIS rank owns, not rank 0's
        return self._guard(
            lambda: self._pg.reduce_scatter(input_chunks, op),
            lambda: [_to_host(a) for a in input_chunks[self._pg.rank()]],
        )

    def alltoall(self, input_chunks):
        return self._guard(
            lambda: self._pg.alltoall(input_chunks),
            lambda: [_to_host(a) for a in input_chunks],
        )

    def send(self, arrays, dst, tag=0):
        return self._guard(lambda: self._pg.send(arrays, dst, tag), lambda: None)

    def recv(self, src, tag=0):
        return self._guard(lambda: self._pg.recv(src, tag), lambda: None)


class FakeProcessGroupWrapper(ProcessGroup):
    """Test-only fault injection: ``report_future_error`` makes the next
    op's future raise (reference: process_group.py:1252-1317), and the
    network-shaped knobs (``times`` for a flaky-link burst, ``delay_ops``
    for a stalled-but-alive wire) let tests reproduce degraded transports
    rather than only clean crashes."""

    def __init__(self, pg: ProcessGroup) -> None:
        super().__init__()
        self._pg = pg
        self._next_error: Optional[Exception] = None
        self._next_error_skip = 0
        self._next_error_times = 0
        self._next_configure_error: Optional[Exception] = None
        # network-stall shape: the next N ops sleep before dispatch
        self._delay_ops_s = 0.0
        self._delay_ops_count = 0
        # test hook: called at the START of prepare_configure (on the
        # quorum thread) — EventInjector uses it to stall the prepare
        # phase past a step boundary deterministically
        self._on_prepare: Optional[Callable[[], None]] = None
        # intra-group member death (degrade plane): the Manager registers
        # a callback here when TORCHFT_DEGRADE=on; dead members accumulate
        # so a test can assert which chips a scenario lost
        self._member_death_cb: Optional[Callable[[int], None]] = None
        self._dead_members: List[int] = []

    @property
    def device_native(self) -> bool:
        return getattr(self._pg, "device_native", False)

    def report_future_error(
        self, e: Exception, skip_ops: int = 0, times: int = 1
    ) -> None:
        """Fail upcoming ops' futures with ``e``. ``skip_ops=k`` lets the
        next k ops through untouched and fails the (k+1)-th — with the
        per-bucket streaming pipeline, that targets bucket k of a plan
        mid-stream instead of only ever the first collective. ``times=n``
        fails n consecutive ops (a flaky link rather than a single drop)."""
        self._next_error = e
        self._next_error_skip = int(skip_ops)
        self._next_error_times = max(1, int(times))

    def delay_ops(self, seconds: float, count: int = 1) -> None:
        """Stall the next ``count`` ops by ``seconds`` before their work
        handle is returned — a slow-but-alive wire, the shape that
        exercises timeout/retry budgets without tripping the error path."""
        self._delay_ops_s = float(seconds)
        self._delay_ops_count = int(count)

    def report_configure_error(self, e: Exception) -> None:
        self._next_configure_error = e

    def set_prepare_hook(self, fn: Optional[Callable[[], None]]) -> None:
        self._on_prepare = fn

    # -- intra-group member death (degrade plane) -------------------------
    def set_member_death_callback(
        self, fn: Optional[Callable[[int], None]]
    ) -> None:
        """Degrade-plane detection hook: the Manager registers its
        report_member_death here (only when TORCHFT_DEGRADE=on), matching
        the abort-watchdog shape a device PG would use on real hardware.
        Also forwarded to the wrapped PG when it has its own support."""
        self._member_death_cb = fn
        setter = getattr(self._pg, "set_member_death_callback", None)
        if setter is not None:
            setter(fn)

    def inject_group_member_death(self, group_rank: int) -> None:
        """Kill chip ``group_rank`` INSIDE this replica's group: the
        intra-group fault the degrade plane survives by resharding onto
        the survivors (EventInjector.kill_chip routes here). Fires the
        registered member-death callback between steps — the
        abort-watchdog detection shape — rather than failing the in-flight
        collective, so the step is re-planned, not discarded."""
        self._dead_members.append(int(group_rank))
        fwd = getattr(self._pg, "inject_group_member_death", None)
        if fwd is not None:
            fwd(group_rank)
        cb = self._member_death_cb
        if cb is not None:
            cb(int(group_rank))

    @property
    def dead_members(self) -> List[int]:
        """Group ranks this wrapper has killed (test assertions)."""
        return list(self._dead_members)

    # -- compressed-ring failover passthroughs ----------------------------
    # (EventInjector.kill_link and the Manager's reroute counter reach the
    # wrapped host PG through these; non-host PGs silently no-op)
    def inject_link_fault(self, src: int, dst: int, at_hop: int = 0) -> None:
        fn = getattr(self._pg, "inject_link_fault", None)
        if fn is not None:
            fn(src, dst, at_hop)

    def clear_link_faults(self) -> None:
        fn = getattr(self._pg, "clear_link_faults", None)
        if fn is not None:
            fn()

    def set_reroute_observer(self, fn) -> None:
        setter = getattr(self._pg, "set_reroute_observer", None)
        if setter is not None:
            setter(fn)

    def configure(self, store_addr, replica_rank, replica_world_size, quorum_id=0):
        if self._next_configure_error is not None:
            e, self._next_configure_error = self._next_configure_error, None
            raise e
        self._pg.configure(store_addr, replica_rank, replica_world_size, quorum_id)

    def prepare_configure(
        self, store_addr, replica_rank, replica_world_size, quorum_id=0
    ) -> Optional[Callable[[], None]]:
        # injection parity with configure(): a staged configure error fires
        # during PREPARE (that is where the real failures live — rendezvous,
        # membership barriers), and the prepare hook runs before it
        if self._on_prepare is not None:
            self._on_prepare()
        if self._next_configure_error is not None:
            e, self._next_configure_error = self._next_configure_error, None
            raise e
        return self._pg.prepare_configure(
            store_addr, replica_rank, replica_world_size, quorum_id=quorum_id
        )

    def abort(self) -> None:
        self._pg.abort()

    def shutdown(self) -> None:
        self._pg.shutdown()

    def errored(self) -> Optional[Exception]:
        return self._pg.errored()

    def size(self) -> int:
        return self._pg.size()

    def rank(self) -> int:
        return self._pg.rank()

    def set_timeout(self, timeout) -> None:
        self._pg.set_timeout(timeout)

    def _maybe_fail(self, work: Work) -> Work:
        if self._delay_ops_count > 0:
            self._delay_ops_count -= 1
            time.sleep(self._delay_ops_s)
        if self._next_error is not None:
            if self._next_error_skip > 0:
                self._next_error_skip -= 1
                return work
            e = self._next_error
            self._next_error_times -= 1
            if self._next_error_times <= 0:
                self._next_error = None
            fut: Future[Any] = Future()

            def _fail(_f: Future[Any]) -> None:
                try:
                    fut.set_exception(e)
                except RuntimeError:
                    pass

            work.get_future().add_done_callback(_fail)
            return FutureWork(fut)
        return work

    def allreduce(self, arrays, op=ReduceOp.SUM):
        return self._maybe_fail(self._pg.allreduce(arrays, op))

    def allgather(self, arrays):
        return self._maybe_fail(self._pg.allgather(arrays))

    def broadcast(self, arrays, root=0):
        return self._maybe_fail(self._pg.broadcast(arrays, root))

    def reduce_scatter(self, input_chunks, op=ReduceOp.SUM):
        return self._maybe_fail(self._pg.reduce_scatter(input_chunks, op))

    def alltoall(self, input_chunks):
        return self._maybe_fail(self._pg.alltoall(input_chunks))

    def send(self, arrays, dst, tag=0):
        return self._maybe_fail(self._pg.send(arrays, dst, tag))

    def recv(self, src, tag=0):
        return self._maybe_fail(self._pg.recv(src, tag))


class ManagedProcessGroup(ProcessGroup):
    """PG adapter whose allreduce routes through a Manager, so unmodified
    data-parallel code picks up quorum participation + error swallowing
    (reference: process_group.py:1320-1353)."""

    def __init__(self, manager: "Any") -> None:  # Manager (avoid cycle)
        super().__init__()
        self._manager = manager

    def allreduce(self, arrays, op=ReduceOp.SUM):
        return self._manager.allreduce(list(arrays), reduce_op=op)

    def size(self) -> int:
        return self._manager.num_participants()

    def rank(self) -> int:
        # replica_rank() is Optional (None before the first quorum); the PG
        # contract is int — report rank 0 until a quorum assigns one.
        r = self._manager.replica_rank()
        return 0 if r is None else r

    def configure(self, store_addr, replica_rank, replica_world_size, quorum_id=0):
        raise RuntimeError("ManagedProcessGroup is configured by its Manager")

    def abort(self) -> None:
        self._manager._pg.abort()

    def shutdown(self) -> None:
        self._manager._pg.shutdown()

    def errored(self) -> Optional[Exception]:
        return self._manager._pg.errored()

    def allgather(self, arrays):
        raise NotImplementedError("managed PG only routes allreduce")

    def broadcast(self, arrays, root=0):
        raise NotImplementedError("managed PG only routes allreduce")

    def reduce_scatter(self, input_chunks, op=ReduceOp.SUM):
        raise NotImplementedError("managed PG only routes allreduce")

    def alltoall(self, input_chunks):
        raise NotImplementedError("managed PG only routes allreduce")

    def send(self, arrays, dst, tag=0):
        raise NotImplementedError("managed PG only routes allreduce")

    def recv(self, src, tag=0):
        raise NotImplementedError("managed PG only routes allreduce")
