"""Standalone lighthouse CLI (reference: src/bin/lighthouse.rs:12-24 and the
structopt flags in src/lighthouse.rs:94-131).

Run one lighthouse per job::

    python -m torchft_tpu.lighthouse --min-replicas 2 --bind 0.0.0.0:29510

Workers point at it via ``TORCHFT_LIGHTHOUSE=http://host:port``. The same
port serves the HTML dashboard (``/``), ``/status`` JSON, the ``/health``
ledger JSON, Prometheus-text ``/metrics``, and per-replica
``POST /replica/{id}/kill``.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from torchft_tpu.coordination import LighthouseServer


def main(argv: "list[str] | None" = None) -> None:
    parser = argparse.ArgumentParser(
        prog="torchft_tpu_lighthouse", description=__doc__
    )
    parser.add_argument("--bind", default="0.0.0.0:29510")
    # each flag also accepts the reference CLI's underscore spelling
    # (src/lighthouse.rs structopt longs are --min_replicas etc.), so a
    # torchft launch script ports without edits
    parser.add_argument("--min-replicas", "--min_replicas", type=int, default=1)
    parser.add_argument(
        "--join-timeout-ms", "--join_timeout_ms", type=int, default=60000
    )
    parser.add_argument(
        "--quorum-tick-ms", "--quorum_tick_ms", type=int, default=100
    )
    parser.add_argument(
        "--heartbeat-timeout-ms", "--heartbeat_timeout_ms", type=int, default=5000
    )
    parser.add_argument(
        "--history",
        default="",
        metavar="PATH",
        help="append-only JSONL of quorum transitions / heals / health "
        "events / telemetry snapshots; replay with "
        "`python -m torchft_tpu.trace history PATH` (default: disabled)",
    )
    parser.add_argument(
        "--serve-registry", "--serve_registry",
        action="store_true",
        help="co-host a serving-plane snapshot registry that health-gates "
        "inference routing off this lighthouse's /health ledger "
        "(docs/serving.md)",
    )
    parser.add_argument(
        "--serve-drain-on", "--serve_drain_on",
        default=None,
        choices=("warn", "eject"),
        help="health state at which the registry drains a serving source "
        "(default: $TORCHFT_SERVE_DRAIN_ON or warn)",
    )
    parser.add_argument(
        "--redundancy-directory", "--redundancy_directory",
        action="store_true",
        help="co-host a redundancy-plane shard directory: tracks "
        "erasure-coded shard placements, detects owner deaths off this "
        "lighthouse's /health ledger, and promotes hot spares "
        "(docs/operations.md); point replicas at it via "
        "TORCHFT_REDUNDANCY_DIRECTORY",
    )
    parser.add_argument(
        "--policy",
        default=None,
        metavar="PATH|builtin",
        help="attach the adaptive policy engine: a PolicySpec JSON file or "
        "'builtin' (docs/operations.md#adaptive-policies). Frames ride the "
        "existing heartbeat/agg_tick replies; what managers DO with them is "
        "governed by TORCHFT_POLICY (off|observe|enforce, default off). "
        "Replay candidates first: "
        "`python -m torchft_tpu.policy replay --history F --policy A B`",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    server = LighthouseServer(
        bind=args.bind,
        min_replicas=args.min_replicas,
        join_timeout_ms=args.join_timeout_ms,
        quorum_tick_ms=args.quorum_tick_ms,
        heartbeat_timeout_ms=args.heartbeat_timeout_ms,
        history_path=args.history,
        serve_registry=args.serve_registry,
        serve_drain_on=args.serve_drain_on,
        redundancy_directory=args.redundancy_directory,
        policy=args.policy,
    )
    logging.info("lighthouse listening at %s", server.address())
    if server.policy_controller is not None:
        logging.info(
            "policy engine attached (spec=%s mode=%s)",
            args.policy,
            server.policy_mode,
        )
    if server.serve_registry is not None:
        logging.info(
            "snapshot registry serving at %s (epoch %s)",
            server.serve_registry.url,
            server.serve_registry.epoch,
        )
    if server.redundancy_directory is not None:
        logging.info(
            "shard directory serving at %s (epoch %s)",
            server.redundancy_directory.url,
            server.redundancy_directory.epoch,
        )

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.shutdown()


if __name__ == "__main__":
    main()
