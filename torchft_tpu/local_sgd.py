"""LocalSGD and (Streaming) DiLoCo: semi-synchronous training algorithms.

Role-equivalent of the reference torchft/local_sgd.py (LocalSGD :45-172,
_StreamingDiLoCoFragment :175-566, DiLoCo :569-795). The JAX translation is
functional: instead of optimizer hooks mutating module parameters, the user
threads the param pytree through ``step()`` after every inner-optimizer
update and gets back the (possibly synced) params.

Semantics preserved from the reference:

- LocalSGD: every ``sync_every`` steps — quorum, allreduce(AVG) of the
  *parameters*, commit vote; on commit adopt the average, on failure restore
  the last synced parameters.
- DiLoCo: inner optimizer runs locally; every ``sync_every`` steps one model
  *fragment* syncs: pseudogradient = global(backup) - local, averaged across
  replica groups (optionally fp8-quantized), outer optimizer steps the
  *global* params, and the new local params are
  ``global.lerp(local, fragment_update_alpha)``. Fragments sync round-robin,
  staggered by ``sync_every / num_fragments`` with ``fragment_sync_delay``
  steps of communication overlap (the "tao" of the Streaming DiLoCo paper).
  Failed commits restore the fragment's backup so no replica over-trains.
- DiLoCo requires synchronous quorum (use_async_quorum=False) so every
  replica syncs the same fragment for the same manager step.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from torchft_tpu.manager import Manager
from torchft_tpu.process_group import ReduceOp
from torchft_tpu.work import Work

logger = logging.getLogger(__name__)

__all__ = ["LocalSGD", "DiLoCo", "partition_fragments"]


def _to_host(tree: Any) -> Any:
    import jax

    return jax.tree_util.tree_map(lambda x: np.array(x, copy=True), tree)


def _like(template: Any, host_tree: Any) -> Any:
    """Place host arrays back like the template leaves (device + sharding)."""
    import jax

    def place(t, h):
        if isinstance(t, jax.Array):
            return jax.device_put(np.asarray(h, dtype=t.dtype), t.sharding)
        return np.asarray(h)

    return jax.tree_util.tree_map(place, template, host_tree)


class LocalSGD:
    """Parameter-averaging LocalSGD (reference: local_sgd.py:45-172).

    Usage::

        local_sgd = LocalSGD(manager, params, sync_every=8)
        for batch in data:
            params, opt_state = inner_step(params, opt_state, batch)
            params = local_sgd.step(params)
    """

    def __init__(self, manager: Manager, params: Any, sync_every: int) -> None:
        assert sync_every >= 1
        self._manager = manager
        self._sync_every = sync_every
        self._local_step = 0
        self._backup = _to_host(params)
        manager.register_state_dict_fn(
            "LocalSGD",
            self._load_state,
            lambda: {"backup": self._backup},
        )

    def _load_state(self, sd: Dict[str, Any]) -> None:
        self._backup = sd["backup"]

    def step(self, params: Any) -> Any:
        """Count an inner step; on the sync boundary average params across
        replica groups. Returns the params to continue training with."""
        self._local_step += 1
        if self._local_step < self._sync_every:
            return params
        self._local_step = 0
        return self._sync(params)

    def _sync(self, params: Any) -> Any:
        # No state-dict write lock here: functional updates rebind the pytree
        # atomically, and holding the write lock across start_quorum would
        # deadlock against the checkpoint server's read lock (the reference
        # locks only around in-place optimizer mutation, local_sgd.py:111-123).
        self._manager.start_quorum()
        work = self._manager.allreduce(params, reduce_op=ReduceOp.AVG)
        averaged = work.get_future().wait()
        if self._manager.should_commit():
            self._backup = _to_host(averaged)
            return _like(params, averaged)
        logger.warning("LocalSGD commit failed; restoring last synced params")
        return _like(params, self._backup)


def partition_fragments(leaves: Sequence[Any], num_fragments: int) -> List[List[int]]:
    """Size-balanced greedy partition of leaf indices into fragments.

    The reference takes explicit nn.Module fragments (user-split via torch
    pipelining, train_diloco.py:152-158); with a flat pytree we can balance
    automatically, and callers may still pass an explicit partition.
    """
    from torchft_tpu.checkpointing._serialization import split_chunks

    sizes = [int(np.asarray(l).nbytes) for l in leaves]
    frags = [sorted(c) for c in split_chunks(sizes, num_fragments)]
    return [f for f in frags if f]


# 1 GiB default bucket cap (reference: local_sgd.py:176)
DEFAULT_BUCKET_CAP_BYTES = 1 << 30


def _make_buckets(
    arrays: List[np.ndarray], cap_bytes: int
) -> List[tuple]:
    """Pack arrays into flat same-dtype buckets of at most ``cap_bytes``.

    Returns ``[(flat_buffer, metas), ...]`` with ``metas = [(arr_index,
    offset, size, shape), ...]``. Fewer, larger collectives amortize the
    per-op framing/pickling overhead of the host DCN plane — the same
    motivation as the reference's bucketized allreduce (local_sgd.py:498-566),
    minus the NCCL-launch angle which does not exist on TPU.
    """
    by_dtype: Dict[Any, List[int]] = {}
    for i, a in enumerate(arrays):
        by_dtype.setdefault(a.dtype, []).append(i)
    # group indices first, pack after: no mutable-closure ordering traps
    groups: List[List[int]] = []
    for idxs in by_dtype.values():
        cur: List[int] = []
        cur_bytes = 0
        for i in idxs:
            nbytes = arrays[i].nbytes
            if cur and cur_bytes + nbytes > cap_bytes:
                groups.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nbytes
        if cur:
            groups.append(cur)
    return [_pack_bucket(arrays, g) for g in groups]


def _pack_bucket(arrays: List[np.ndarray], idxs: List[int]) -> tuple:
    metas = []
    offset = 0
    for i in idxs:
        a = arrays[i]
        metas.append((i, offset, a.size, a.shape))
        offset += a.size
    flat = np.empty(offset, dtype=arrays[idxs[0]].dtype)
    for (i, off, size, _shape) in metas:
        flat[off : off + size] = arrays[i].reshape(-1)
    return flat, metas


def _unpack_buckets(buckets_out: List[np.ndarray], bucket_metas: List[List[tuple]], n: int) -> List[np.ndarray]:
    out: List[Optional[np.ndarray]] = [None] * n
    for flat, metas in zip(buckets_out, bucket_metas):
        flat = np.asarray(flat)
        for (i, off, size, shape) in metas:
            out[i] = flat[off : off + size].reshape(shape)
    assert all(o is not None for o in out)
    return out  # type: ignore[return-value]


class _Fragment:
    """One fragment's state: global (backup) params + outer optimizer state +
    in-flight allreduce (reference _StreamingDiLoCoFragment)."""

    def __init__(
        self,
        manager: Manager,
        fragment_id: int,
        leaf_indices: List[int],
        leaves: List[Any],
        outer_tx: "optax.GradientTransformation",
        fragment_update_alpha: float,
        should_quantize: bool,
        use_bucketization: bool = False,
        bucket_cap_bytes: int = DEFAULT_BUCKET_CAP_BYTES,
    ) -> None:
        import optax  # noqa: F401  (typing only)

        self._manager = manager
        self._id = fragment_id
        self.leaf_indices = leaf_indices
        self._outer_tx = outer_tx
        self._alpha = fragment_update_alpha
        self._should_quantize = should_quantize
        self._use_bucketization = use_bucketization
        self._bucket_cap_bytes = bucket_cap_bytes
        self._bucket_metas: Optional[List[List[tuple]]] = None

        # global ("original") parameters live on host, like the reference's
        # CPU backups (local_sgd.py:241-253)
        self.original: List[np.ndarray] = [np.array(leaves[i], copy=True) for i in leaf_indices]
        self.outer_state = outer_tx.init(self.original)
        self._work: Optional[Work] = None
        self._pending_grads: Optional[List[np.ndarray]] = None

        manager.register_state_dict_fn(
            f"StreamingDiLoCoFragment_{fragment_id}",
            self._load_state,
            self._save_state,
        )

    def _save_state(self) -> Dict[str, Any]:
        return {
            "original_parameters": [p.copy() for p in self.original],
            "outer_optimizer": self.outer_state,
        }

    def _load_state(self, sd: Dict[str, Any]) -> None:
        self.original = [np.asarray(p) for p in sd["original_parameters"]]
        self.outer_state = sd["outer_optimizer"]

    # -- sync phases ------------------------------------------------------
    def prepare_sync(self, leaves: List[Any]) -> None:
        """Pseudogradient = global - local, issue async averaged allreduce
        (reference: local_sgd.py:401-420)."""
        pseudograds = [
            (self.original[k] - np.asarray(leaves[i])).astype(self.original[k].dtype)
            for k, i in enumerate(self.leaf_indices)
        ]
        assert self._work is None, "fragment already has an allreduce in flight"
        # Quantized allreduce already concatenates everything into one flat
        # wire buffer (collectives.py), so pre-bucketing there would add a
        # redundant copy AND shift fp8 rowwise-scale boundaries (changing
        # numerics). Bucketize only the unquantized path.
        if (
            self._use_bucketization
            and not self._should_quantize
            and len(pseudograds) > 1
        ):
            buckets = _make_buckets(pseudograds, self._bucket_cap_bytes)
            self._bucket_metas = [metas for _flat, metas in buckets]
            self._work = self._manager.allreduce(
                [flat for flat, _metas in buckets],
                should_quantize=self._should_quantize,
            )
        else:
            self._bucket_metas = None
            self._work = self._manager.allreduce(
                pseudograds, should_quantize=self._should_quantize
            )

    def perform_sync(self, leaves: List[Any]) -> bool:
        """Wait for the allreduce, vote, outer-step on commit
        (reference: local_sgd.py:422-475). Mutates ``leaves`` in place with
        the fragment's new local values. Returns should_commit."""
        import optax

        assert self._work is not None, "perform_sync before prepare_sync"
        avg_pseudograds = self._work.get_future().wait()
        self._work = None
        if self._bucket_metas is not None:
            avg_pseudograds = _unpack_buckets(
                avg_pseudograds, self._bucket_metas, len(self.leaf_indices)
            )
            self._bucket_metas = None

        # save local, restore global (rollback point)
        local = [np.array(leaves[i], copy=True) for i in self.leaf_indices]
        restored = list(self.original)

        should_commit = self._manager.should_commit()
        if should_commit:
            grads = [np.asarray(g) for g in avg_pseudograds]
            updates, self.outer_state = self._outer_tx.update(
                grads, self.outer_state, restored
            )
            new_global = optax.apply_updates(restored, updates)
            new_global = [np.asarray(p) for p in new_global]
            self.original = [p.copy() for p in new_global]
            # merge: global.lerp(local, alpha)
            merged = [
                (g + self._alpha * (l - g)).astype(g.dtype)
                for g, l in zip(new_global, local)
            ]
            for k, i in enumerate(self.leaf_indices):
                leaves[i] = merged[k]
        else:
            logger.warning(
                f"DiLoCo fragment {self._id}: commit failed; restoring global params"
            )
            for k, i in enumerate(self.leaf_indices):
                leaves[i] = restored[k].copy()
        return should_commit


class DiLoCo:
    """Streaming DiLoCo over a param pytree (reference: local_sgd.py:569-795).

    Usage::

        diloco = DiLoCo(manager, params, outer_tx=optax.sgd(0.7, momentum=0.9,
                        nesterov=True), sync_every=20, num_fragments=2)
        for batch in data:
            params, inner_state = inner_step(params, inner_state, batch)
            params = diloco.step(params)
    """

    def __init__(
        self,
        manager: Manager,
        params: Any,
        outer_tx: "optax.GradientTransformation",
        sync_every: int,
        num_fragments: int = 1,
        fragment_partition: Optional[List[List[int]]] = None,
        fragment_sync_delay: int = 0,
        fragment_update_alpha: float = 0.0,
        should_quantize: bool = False,
        use_bucketization: Optional[bool] = None,
        bucket_cap_mb: Optional[int] = None,
    ) -> None:
        import jax

        # TORCHFT_USE_BUCKETIZATION matches the reference's precedence
        # (local_sgd.py:225-228): the env var force-enables bucketization
        # even when the constructor passed use_bucketization=False; it never
        # force-disables.
        env_bucketization = os.environ.get(
            "TORCHFT_USE_BUCKETIZATION", "false"
        ).lower() in ("1", "true", "yes")
        use_bucketization = env_bucketization or bool(use_bucketization)
        bucket_cap_bytes = (
            bucket_cap_mb * 1024 * 1024
            if bucket_cap_mb is not None
            else DEFAULT_BUCKET_CAP_BYTES
        )

        if manager._use_async_quorum:
            raise ValueError(
                "DiLoCo requires synchronous quorum: construct the Manager "
                "with use_async_quorum=False"
            )
        leaves, self._treedef = jax.tree_util.tree_flatten(params)
        if fragment_partition is None:
            fragment_partition = partition_fragments(leaves, num_fragments)
        num_fragments = len(fragment_partition)
        if sync_every < num_fragments:
            raise ValueError("only 1 fragment can be synchronized at a time")
        if sync_every % num_fragments != 0:
            raise ValueError("sync_every must be divisible by num_fragments")
        # per-fragment cycle length (reference: local_sgd.py:634)
        self._sync_every = sync_every // num_fragments
        if fragment_sync_delay >= self._sync_every:
            raise ValueError("fragment must sync before it is reduced again")
        if not 0.0 <= fragment_update_alpha <= 1.0:
            raise ValueError("fragment_update_alpha must be in [0, 1]")

        self._manager = manager
        self._local_step = 0
        self._delay = fragment_sync_delay
        self._fragments = [
            _Fragment(
                manager, i, idxs, leaves, outer_tx,
                fragment_update_alpha, should_quantize,
                use_bucketization=use_bucketization,
                bucket_cap_bytes=bucket_cap_bytes,
            )
            for i, idxs in enumerate(fragment_partition)
        ]

    def _current_fragment(self) -> int:
        # All replicas pick the fragment from the shared manager step so they
        # never deadlock sending different fragments (reference comment,
        # local_sgd.py:753-762).
        return self._manager.current_step() % len(self._fragments)

    def step(self, params: Any) -> Any:
        """Advance one inner step; returns params (synced on boundaries)."""
        import jax

        self._local_step += 1

        leaves, treedef = jax.tree_util.tree_flatten(params)
        changed = False

        if self._local_step == self._sync_every - self._delay:
            # prepare: overlap the allreduce with the next `delay` steps
            self._manager.start_quorum()
            frag = self._current_fragment()
            logger.info(f"DiLoCo: preparing fragment={frag} step={self._local_step}")
            self._fragments[frag].prepare_sync(leaves)

        changed_indices: List[int] = []
        if self._local_step == self._sync_every:
            frag = self._current_fragment()
            logger.info(
                f"DiLoCo: syncing fragment={frag} manager_step={self._manager.current_step()}"
            )
            self._fragments[frag].perform_sync(leaves)
            changed_indices = self._fragments[frag].leaf_indices
            self._local_step = 0

        if not changed_indices:
            return params
        # Re-place only the synced fragment's leaves; the other fragments'
        # jax.Arrays pass through untouched (streaming DiLoCo's point is that
        # a sync boundary touches 1/num_fragments of the model).
        orig_leaves = jax.tree_util.tree_leaves(params)
        for i in changed_indices:
            orig = orig_leaves[i]
            if isinstance(orig, jax.Array):
                leaves[i] = jax.device_put(
                    np.asarray(leaves[i], dtype=orig.dtype), orig.sharding
                )
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # introspection used by tests
    @property
    def fragments(self) -> List[_Fragment]:
        return self._fragments
