"""LocalSGD and (Streaming) DiLoCo: semi-synchronous training algorithms.

Role-equivalent of the reference torchft/local_sgd.py (LocalSGD :45-172,
_StreamingDiLoCoFragment :175-566, DiLoCo :569-795). The JAX translation is
functional: instead of optimizer hooks mutating module parameters, the user
threads the param pytree through ``step()`` after every inner-optimizer
update and gets back the (possibly synced) params.

Semantics preserved from the reference:

- LocalSGD: every ``sync_every`` steps — quorum, allreduce(AVG) of the
  *parameters*, commit vote; on commit adopt the average, on failure restore
  the last synced parameters.
- DiLoCo: inner optimizer runs locally; every ``sync_every`` steps one model
  *fragment* syncs: pseudogradient = global(backup) - local, averaged across
  replica groups (optionally fp8-quantized), outer optimizer steps the
  *global* params, and the new local params are
  ``global.lerp(local, fragment_update_alpha)``. Fragments sync round-robin,
  staggered by ``sync_every / num_fragments`` with ``fragment_sync_delay``
  steps of communication overlap (the "tao" of the Streaming DiLoCo paper).
  Failed commits restore the fragment's backup so no replica over-trains.
- DiLoCo requires synchronous quorum (use_async_quorum=False) so every
  replica syncs the same fragment for the same manager step.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from torchft_tpu import knobs
from torchft_tpu.manager import Manager
from torchft_tpu.process_group import ReduceOp
from torchft_tpu.work import Work

logger = logging.getLogger(__name__)

__all__ = ["LocalSGD", "DiLoCo", "partition_fragments"]


def _snapshot(tree: Any) -> Any:
    """Rollback copy of a pytree, donation-safe.

    jax.Arrays are immutable but NOT deletion-proof: a train step jitted
    with ``donate_argnums`` (the production default, parallel/mesh.py)
    deletes the caller's param buffers, so a snapshot that merely holds the
    reference dies with them. ``jnp.copy`` allocates a distinct device
    buffer (same sharding) that donation can't touch."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array)
        else np.array(x, copy=True),
        tree,
    )


def _like(template: Any, values: Any) -> Any:
    """Place values back like the template leaves (device + sharding).
    Device-resident values take the zero-copy `device_put` path; host arrays
    are uploaded."""
    import jax

    def place(t, h):
        if isinstance(t, jax.Array):
            if isinstance(h, jax.Array) and h.dtype == t.dtype:
                return jax.device_put(h, t.sharding)
            return jax.device_put(np.asarray(h, dtype=t.dtype), t.sharding)
        return np.asarray(h)

    return jax.tree_util.tree_map(place, template, values)


def _nbytes(leaf: Any) -> int:
    """Leaf size in bytes without forcing a device→host transfer."""
    if hasattr(leaf, "nbytes"):
        return int(leaf.nbytes)
    return int(np.asarray(leaf).nbytes)


class LocalSGD:
    """Parameter-averaging LocalSGD (reference: local_sgd.py:45-172).

    Usage::

        local_sgd = LocalSGD(manager, params, sync_every=8)
        for batch in data:
            params, opt_state = inner_step(params, opt_state, batch)
            params = local_sgd.step(params)
    """

    def __init__(
        self,
        manager: Manager,
        params: Any,
        sync_every: int,
        get_params: Optional[Any] = None,
    ) -> None:
        assert sync_every >= 1
        self._manager = manager
        # TORCHFT_SYNC_EVERY > 0 (env or policy override) beats the
        # constructor arg, so the same launch script can be retargeted
        # without a code change; 0 (the default) means "use the arg".
        env_sync = knobs.env_int("TORCHFT_SYNC_EVERY", 0)
        self._sync_every = env_sync if env_sync > 0 else sync_every
        self._arg_sync_every = self._sync_every
        self._local_step = 0
        # test doubles and minimal manager stand-ins may not carry the
        # policy surface — live retargeting is an optional capability
        register = getattr(manager, "register_policy_adjuster", None)
        if register is not None:
            register("TORCHFT_SYNC_EVERY", self._policy_set_sync_every)
        # get_params only matters for sync-quorum managers: with async quorum
        # a healing replica is non-participating, so Manager.allreduce zeros
        # its contribution and the averaged result it adopts is built from
        # healthy peers only — no staleness can leak into the group. On a
        # sync-quorum heal without get_params, _sync falls back to averaging
        # the healed backup.
        self._get_params = get_params
        self._backup = _snapshot(params)
        manager.register_state_dict_fn(
            "LocalSGD",
            self._load_state,
            lambda: {"backup": self._backup},
        )

    def _load_state(self, sd: Dict[str, Any]) -> None:
        self._backup = sd["backup"]

    @property
    def sync_every(self) -> int:
        return self._sync_every

    def set_sync_every(self, sync_every: int) -> None:
        """Live-retarget the sync cadence. Safe at any inner step: a
        longer cadence simply pushes the next boundary out; a shorter one
        syncs on the next ``step`` whose counter has already crossed it."""
        assert sync_every >= 1
        self._sync_every = sync_every

    def _policy_set_sync_every(self, value: Optional[str]) -> None:
        if value is None:
            self.set_sync_every(self._arg_sync_every)
        else:
            self.set_sync_every(max(1, int(value)))

    def step(self, params: Any) -> Any:
        """Count an inner step; on the sync boundary average params across
        replica groups. Returns the params to continue training with."""
        self._local_step += 1
        if self._local_step < self._sync_every:
            return params
        self._local_step = 0
        return self._sync(params)

    def _sync(self, params: Any) -> Any:
        # No state-dict write lock here: functional updates rebind the pytree
        # atomically, and holding the write lock across start_quorum would
        # deadlock against the checkpoint server's read lock (the reference
        # locks only around in-place optimizer mutation, local_sgd.py:111-123).
        self._manager.start_quorum()
        if self._manager.last_quorum_healed():
            # a sync-quorum heal rebound the caller's state; the `params`
            # captured before start_quorum are stale and must not be
            # averaged into the group
            if self._get_params is not None:
                params = self._get_params()
            else:
                # fallback: our own registered load fn just healed the
                # backup (the peer's last synced params) — average that
                logger.warning(
                    "LocalSGD: healed without get_params; averaging the "
                    "recovered backup instead of the stale local params"
                )
                params = _like(params, _snapshot(self._backup))
        work = self._manager.allreduce(params, reduce_op=ReduceOp.AVG)
        averaged = work.get_future().wait()
        if self._manager.should_commit():
            self._backup = _snapshot(averaged)
            return _like(params, averaged)
        logger.warning("LocalSGD commit failed; restoring last synced params")
        # snapshot again on the way out: the returned params may be donated
        # by the caller's train step, which must not delete the backup
        return _like(params, _snapshot(self._backup))


def partition_fragments(leaves: Sequence[Any], num_fragments: int) -> List[List[int]]:
    """Size-balanced greedy partition of leaf indices into fragments.

    The reference takes explicit nn.Module fragments (user-split via torch
    pipelining, train_diloco.py:152-158); with a flat pytree we can balance
    automatically, and callers may still pass an explicit partition.
    """
    from torchft_tpu.checkpointing._serialization import split_chunks

    sizes = [_nbytes(l) for l in leaves]
    frags = [sorted(c) for c in split_chunks(sizes, num_fragments)]
    return [f for f in frags if f]


# Bucketing lives in the shared torchft_tpu/bucketing.py (used by
# Manager.allreduce and ddp.py as well); the underscore names are the
# original home of these helpers, kept importable for callers and tests.
from torchft_tpu.bucketing import (  # noqa: E402
    DEFAULT_BUCKET_CAP_BYTES,
    make_buckets as _make_buckets,
    pack_group as _pack_bucket,
    unpack_buckets as _unpack_buckets,
)


class _Fragment:
    """One fragment's state: global (backup) params + outer optimizer state +
    in-flight allreduce (reference _StreamingDiLoCoFragment).

    Two execution modes, picked per fragment from the leaf types:

    - **device** (all leaves are jax.Arrays — the production path): global
      params and outer optimizer state stay device-resident with the leaves'
      shardings, and pseudogradient / outer step / merge run as jitted
      functions. Nothing crosses to the host except whatever the configured
      data plane itself ships (nothing for ProcessGroupXLA; fp8 payloads for
      the quantized path; raw frames for the host plane). The reference's
      equivalent is its GPU-resident backup option (local_sgd.py:241-253).
    - **host** (numpy leaves — tests, CPU-plane experiments): numpy backups
      and a numpy outer step, as before.
    """

    def __init__(
        self,
        manager: Manager,
        fragment_id: int,
        leaf_indices: List[int],
        leaves: List[Any],
        outer_tx: "optax.GradientTransformation",
        fragment_update_alpha: float,
        should_quantize: bool,
        use_bucketization: bool = False,
        bucket_cap_bytes: int = DEFAULT_BUCKET_CAP_BYTES,
    ) -> None:
        import jax
        import optax  # noqa: F401  (typing only)

        self._manager = manager
        self._id = fragment_id
        self.leaf_indices = leaf_indices
        self._outer_tx = outer_tx
        self._alpha = fragment_update_alpha
        self._should_quantize = should_quantize
        self._use_bucketization = use_bucketization
        self._bucket_cap_bytes = bucket_cap_bytes
        self._bucket_metas: Optional[List[List[tuple]]] = None

        self._on_device = all(
            isinstance(leaves[i], jax.Array) for i in leaf_indices
        )
        if self._on_device:
            import jax.numpy as jnp

            # device-resident globals in fragment-private buffers: the
            # caller's train step may donate (delete) its param buffers,
            # so aliasing them would kill the backup (see _snapshot)
            self.original: List[Any] = [
                jnp.copy(leaves[i]) for i in leaf_indices
            ]
        else:
            # host mode mirrors the reference's CPU backups
            # (local_sgd.py:241-253)
            self.original = [
                np.array(leaves[i], copy=True) for i in leaf_indices
            ]
        self.outer_state = outer_tx.init(self.original)
        self._work: Optional[Work] = None
        self._pending_grads: Optional[List[np.ndarray]] = None

        if self._on_device:
            alpha = self._alpha

            def _pseudograd(original, local):
                return [
                    (o - l).astype(o.dtype) for o, l in zip(original, local)
                ]

            def _outer_step(grads, state, original, local):
                updates, new_state = outer_tx.update(grads, state, original)
                new_global = optax.apply_updates(original, updates)
                merged = [
                    (g + alpha * (l - g)).astype(g.dtype)
                    for g, l in zip(new_global, local)
                ]
                return new_global, new_state, merged

            self._pseudograd_jit = jax.jit(_pseudograd)
            self._outer_step_jit = jax.jit(_outer_step)

        manager.register_state_dict_fn(
            f"StreamingDiLoCoFragment_{fragment_id}",
            self._load_state,
            self._save_state,
        )

    def _save_state(self) -> Dict[str, Any]:
        return {
            "original_parameters": [
                p if self._on_device else p.copy() for p in self.original
            ],
            "outer_optimizer": self.outer_state,
        }

    def _load_state(self, sd: Dict[str, Any]) -> None:
        import jax

        incoming = list(sd["original_parameters"])
        if self._on_device:
            # recovered state may arrive as host arrays (HTTP transport);
            # restore it to the fragment's device placement
            self.original = [
                _like(t, p) for t, p in zip(self.original, incoming)
            ]
            self.outer_state = jax.tree_util.tree_map(
                lambda t, p: _like(t, p) if isinstance(t, jax.Array) else p,
                self.outer_state,
                sd["outer_optimizer"],
            )
        else:
            self.original = [np.asarray(p) for p in incoming]
            self.outer_state = sd["outer_optimizer"]

    # -- sync phases ------------------------------------------------------
    def prepare_sync(self, leaves: List[Any]) -> None:
        """Pseudogradient = global - local, issue async averaged allreduce
        (reference: local_sgd.py:401-420)."""
        if self._on_device:
            pseudograds = self._pseudograd_jit(
                self.original, [leaves[i] for i in self.leaf_indices]
            )
        else:
            pseudograds = [
                (self.original[k] - np.asarray(leaves[i])).astype(
                    self.original[k].dtype
                )
                for k, i in enumerate(self.leaf_indices)
            ]
        assert self._work is None, "fragment already has an allreduce in flight"
        # Pre-bucket only the unquantized path. Quantized pseudogradients
        # go to the Manager whole: it streams them as compressed buckets
        # with error feedback where supported (host PG, streaming on), and
        # its MONOLITHIC fallback (collectives.py) concatenates into one
        # flat wire buffer — pre-bucketing here would add a redundant copy
        # and pin codec boundaries the Manager already owns.
        if (
            self._use_bucketization
            and not self._should_quantize
            and len(pseudograds) > 1
        ):
            buckets = _make_buckets(pseudograds, self._bucket_cap_bytes)
            self._bucket_metas = [metas for _flat, metas in buckets]
            self._work = self._manager.allreduce(
                [flat for flat, _metas in buckets],
                should_quantize=self._should_quantize,
            )
        else:
            self._bucket_metas = None
            self._work = self._manager.allreduce(
                pseudograds, should_quantize=self._should_quantize
            )

    def perform_sync(self, leaves: List[Any]) -> bool:
        """Wait for the allreduce, vote, outer-step on commit
        (reference: local_sgd.py:422-475). Mutates ``leaves`` in place with
        the fragment's new local values. Returns should_commit."""
        import optax

        assert self._work is not None, "perform_sync before prepare_sync"
        avg_pseudograds = self._work.get_future().wait()
        self._work = None
        if self._bucket_metas is not None:
            avg_pseudograds = _unpack_buckets(
                avg_pseudograds, self._bucket_metas, len(self.leaf_indices)
            )
            self._bucket_metas = None

        # save local, restore global (rollback point)
        if self._on_device:
            local = [leaves[i] for i in self.leaf_indices]  # immutable
        else:
            local = [np.array(leaves[i], copy=True) for i in self.leaf_indices]
        restored = list(self.original)

        should_commit = self._manager.should_commit()
        if should_commit:
            if self._on_device:
                import jax.numpy as jnp

                grads = [
                    _like(t, g) for t, g in zip(restored, avg_pseudograds)
                ]
                new_global, self.outer_state, merged = self._outer_step_jit(
                    grads, self.outer_state, restored, local
                )
                # private eager copies: with alpha=0 XLA may alias the
                # merged and new_global outvars to one buffer, and merged
                # is handed to a (possibly donating) caller
                self.original = [jnp.copy(g) for g in new_global]
            else:
                grads = [np.asarray(g) for g in avg_pseudograds]
                updates, self.outer_state = self._outer_tx.update(
                    grads, self.outer_state, restored
                )
                new_global = optax.apply_updates(restored, updates)
                new_global = [np.asarray(p) for p in new_global]
                self.original = [p.copy() for p in new_global]
                # merge: global.lerp(local, alpha)
                merged = [
                    (g + self._alpha * (l - g)).astype(g.dtype)
                    for g, l in zip(new_global, local)
                ]
            for k, i in enumerate(self.leaf_indices):
                leaves[i] = merged[k]
        else:
            import jax.numpy as jnp

            logger.warning(
                f"DiLoCo fragment {self._id}: commit failed; restoring global params"
            )
            for k, i in enumerate(self.leaf_indices):
                # hand out a copy: the caller may donate what we return,
                # which must never delete the fragment-private backup
                leaves[i] = (
                    jnp.copy(restored[k])
                    if self._on_device
                    else restored[k].copy()
                )
        return should_commit


class DiLoCo:
    """Streaming DiLoCo over a param pytree (reference: local_sgd.py:569-795).

    Usage::

        diloco = DiLoCo(manager, params, outer_tx=optax.sgd(0.7, momentum=0.9,
                        nesterov=True), sync_every=20, num_fragments=2)
        for batch in data:
            params, inner_state = inner_step(params, inner_state, batch)
            params = diloco.step(params)
    """

    def __init__(
        self,
        manager: Manager,
        params: Any,
        outer_tx: "optax.GradientTransformation",
        sync_every: int,
        num_fragments: int = 1,
        fragment_partition: Optional[List[List[int]]] = None,
        fragment_sync_delay: int = 0,
        fragment_update_alpha: float = 0.0,
        should_quantize: bool = False,
        use_bucketization: Optional[bool] = None,
        bucket_cap_mb: Optional[int] = None,
        get_params: Optional[Any] = None,
    ) -> None:
        import jax

        # TORCHFT_USE_BUCKETIZATION matches the reference's precedence
        # (local_sgd.py:225-228): the env var force-enables bucketization
        # even when the constructor passed use_bucketization=False; it never
        # force-disables.
        from torchft_tpu import knobs

        env_bucketization = knobs.env_bool("TORCHFT_USE_BUCKETIZATION")
        use_bucketization = env_bucketization or bool(use_bucketization)
        # TORCHFT_SYNC_EVERY > 0 (env or policy override) replaces the
        # constructor's total cadence; it goes through the same
        # divisibility validation below, so a bad value fails fast.
        env_sync = knobs.env_int("TORCHFT_SYNC_EVERY", 0)
        if env_sync > 0:
            sync_every = env_sync
        bucket_cap_bytes = (
            bucket_cap_mb * 1024 * 1024
            if bucket_cap_mb is not None
            else DEFAULT_BUCKET_CAP_BYTES
        )

        if manager._use_async_quorum:
            raise ValueError(
                "DiLoCo requires synchronous quorum: construct the Manager "
                "with use_async_quorum=False"
            )
        leaves, self._treedef = jax.tree_util.tree_flatten(params)
        if fragment_partition is None:
            fragment_partition = partition_fragments(leaves, num_fragments)
        num_fragments = len(fragment_partition)
        if sync_every < num_fragments:
            raise ValueError("only 1 fragment can be synchronized at a time")
        if sync_every % num_fragments != 0:
            raise ValueError("sync_every must be divisible by num_fragments")
        # per-fragment cycle length (reference: local_sgd.py:634)
        self._sync_every = sync_every // num_fragments
        if fragment_sync_delay >= self._sync_every:
            raise ValueError("fragment must sync before it is reduced again")
        if not 0.0 <= fragment_update_alpha <= 1.0:
            raise ValueError("fragment_update_alpha must be in [0, 1]")

        self._manager = manager
        self._local_step = 0
        self._delay = fragment_sync_delay
        # functional heal hook: after a sync-quorum live recovery the user's
        # param pytree is rebound by their registered load fn, so leaves
        # captured before start_quorum are stale. get_params() re-reads the
        # authoritative (healed) pytree. The reference never faces this —
        # torch heals nn.Module tensors in place (manager.py:819-846) and
        # the module reference stays valid.
        self._get_params = get_params
        self._fragments = [
            _Fragment(
                manager, i, idxs, leaves, outer_tx,
                fragment_update_alpha, should_quantize,
                use_bucketization=use_bucketization,
                bucket_cap_bytes=bucket_cap_bytes,
            )
            for i, idxs in enumerate(fragment_partition)
        ]
        self._arg_sync_every = self._sync_every
        self._pending_sync_every: Optional[int] = None
        # same optional-capability contract as LocalSGD above
        register = getattr(manager, "register_policy_adjuster", None)
        if register is not None:
            register("TORCHFT_SYNC_EVERY", self._policy_set_sync_every)

    @property
    def sync_every(self) -> int:
        """Per-fragment cycle length currently in force."""
        return self._sync_every

    def set_sync_every(self, sync_every: int) -> None:
        """Queue a live retarget of the total sync cadence. Validated
        like the constructor (positive multiple of num_fragments, longer
        than the fragment delay); applied at the next cycle boundary so
        an in-flight prepare/perform pair is never split."""
        n = len(self._fragments)
        if sync_every < n or sync_every % n != 0:
            raise ValueError(
                "sync_every must be a positive multiple of num_fragments"
            )
        per = sync_every // n
        if self._delay >= per:
            raise ValueError("fragment must sync before it is reduced again")
        self._pending_sync_every = per

    def _policy_set_sync_every(self, value: Optional[str]) -> None:
        if value is None:
            self._pending_sync_every = self._arg_sync_every
            return
        # policy values are advisory — clamp into the legal range instead
        # of raising at the quorum safe point
        n = len(self._fragments)
        per = max(int(value) // n, self._delay + 1, 1)
        self._pending_sync_every = per

    def _current_fragment(self) -> int:
        # All replicas pick the fragment from the shared manager step so they
        # never deadlock sending different fragments (reference comment,
        # local_sgd.py:753-762).
        return self._manager.current_step() % len(self._fragments)

    def step(self, params: Any) -> Any:
        """Advance one inner step; returns params (synced on boundaries)."""
        import jax

        # cycle boundary: a policy retarget queued mid-cycle lands here,
        # where the equality-based prepare/perform triggers below cannot
        # be skipped over by a shrinking cadence
        if self._local_step == 0 and self._pending_sync_every is not None:
            self._sync_every = self._pending_sync_every
            self._pending_sync_every = None
        self._local_step += 1

        leaves, treedef = jax.tree_util.tree_flatten(params)
        healed_fallback_indices: List[int] = []

        if self._local_step == self._sync_every - self._delay:
            # prepare: overlap the allreduce with the next `delay` steps
            self._manager.start_quorum()
            if self._manager.last_quorum_healed():
                # The quorum live-healed this replica: fragment globals and
                # the user's params were rebound by the registered load fns,
                # so the leaves flattened from the pre-heal pytree are stale —
                # pseudogradients from them would be garbage AVERAGED INTO
                # EVERY replica group.
                if self._get_params is not None:
                    # re-read the healed pytree: pseudograd = original -
                    # healed_local, the reference's semantics (its in-place
                    # module heal makes this automatic)
                    params = self._get_params()
                    leaves, treedef = jax.tree_util.tree_flatten(params)
                else:
                    # safe fallback: treat the healed replica as having no
                    # local drift (local := healed original → zero
                    # pseudograd). Conservative but never corrupting.
                    logger.warning(
                        "DiLoCo: healed without get_params; contributing "
                        "zero pseudogradient this cycle (pass get_params "
                        "for full-fidelity post-heal syncs)"
                    )
                    import jax.numpy as jnp

                    for frag_ in self._fragments:
                        for k, i in enumerate(frag_.leaf_indices):
                            # always a copy: host callers may mutate in
                            # place, device callers may donate — neither
                            # must reach the fragment's private backup
                            leaves[i] = (
                                jnp.copy(frag_.original[k])
                                if frag_._on_device
                                else frag_.original[k].copy()
                            )
                            # must survive into the returned pytree even
                            # when this boundary performs no sync (delay>0)
                            healed_fallback_indices.append(i)
            frag = self._current_fragment()
            logger.info(f"DiLoCo: preparing fragment={frag} step={self._local_step}")
            self._fragments[frag].prepare_sync(leaves)

        changed_indices: List[int] = []
        if self._local_step == self._sync_every:
            frag = self._current_fragment()
            logger.info(
                f"DiLoCo: syncing fragment={frag} manager_step={self._manager.current_step()}"
            )
            self._fragments[frag].perform_sync(leaves)
            changed_indices = self._fragments[frag].leaf_indices
            self._local_step = 0

        changed_indices = sorted(
            set(changed_indices) | set(healed_fallback_indices)
        )
        if not changed_indices:
            return params
        return self._replace_synced(params, leaves, treedef, changed_indices)

    @staticmethod
    def _replace_synced(
        params: Any, leaves: List[Any], treedef: Any, changed: List[int]
    ) -> Any:
        """Rebuild params with the synced leaves re-placed onto their
        original device/sharding. Only the changed indices are touched —
        the other fragments' jax.Arrays pass through untouched (streaming
        DiLoCo's point is that a sync boundary touches 1/num_fragments of
        the model)."""
        import jax

        orig_leaves = jax.tree_util.tree_leaves(params)
        for i in changed:
            orig = orig_leaves[i]
            if isinstance(orig, jax.Array):
                # device-path leaves are already jax.Arrays — _like is a
                # zero-copy device_put to the original sharding
                leaves[i] = _like(orig, leaves[i])
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def flush(self, params: Any) -> Any:
        """Complete any in-flight fragment sync: waits the pseudogradient
        allreduce, casts the two-phase commit vote, applies the outer step.

        Call before shutting down a trainer whose loop may stop between a
        prepare boundary and its perform boundary (``fragment_sync_delay >
        0``) — abandoning the in-flight collective would leave peers waiting
        on a commit round this replica never votes. No-op when nothing is
        in flight. Returns the (possibly synced) params.
        """
        import jax

        pending = [f for f in self._fragments if f._work is not None]
        if not pending:
            return params
        leaves, treedef = jax.tree_util.tree_flatten(params)
        changed: List[int] = []
        for frag in pending:
            logger.info(f"DiLoCo: flushing in-flight sync of fragment {frag._id}")
            frag.perform_sync(leaves)
            changed.extend(frag.leaf_indices)
        self._local_step = 0
        return self._replace_synced(params, leaves, treedef, changed)

    # introspection used by tests
    @property
    def fragments(self) -> List[_Fragment]:
        return self._fragments
