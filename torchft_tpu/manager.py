"""Manager: the per-worker fault-tolerance state machine.

Role-equivalent of the reference Manager (torchft/manager.py:148-1046). Owns
the quorum lifecycle (async on a one-thread executor), process-group
reconfiguration per quorum, live healing (send/recv checkpoint between
replica groups), error capture with swallow-to-default semantics, the
two-phase commit protocol, and step/batches accounting.

JAX-flavored deviations from the reference, by design:

- **State is a pytree.** Registered state-dict functions return/accept JAX
  pytrees; "zero the tensor on error" becomes *returning a zeros pytree*
  (arrays are immutable, so corrupt in-flight buffers can simply be dropped).
- **No stream plumbing.** JAX has no user streams; the recovery "stream" is
  the quorum executor thread, and ``should_commit`` joins it instead of
  synchronizing a CUDA event (reference manager.py:873-885).
- **Eager future chains.** The reference's lazy ``_ManagedWork`` machinery
  exists to avoid blocking CUDA streams from Python; with host-side
  collectives + async dispatch there is nothing to block, so futures chain
  eagerly.
"""

from __future__ import annotations

import logging
import os
import socket as _socket
import threading
import time
import traceback
import uuid
import weakref
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from pathlib import Path
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, TypeVar, cast

import numpy as np

from torchft_tpu import bucketing, knobs
from torchft_tpu.checkpointing import CheckpointTransport, HTTPTransport, RWLock
from torchft_tpu.coordination import (
    KvClient,
    KvStoreServer,
    ManagerClient,
    ManagerServer,
)
from torchft_tpu.futures import future_timeout
from torchft_tpu.observability import (
    ALLREDUCE_PIPELINE_PHASE,
    COMMIT_EVENTS,
    HEALTH_EVENTS,
    METRICS_PORT_ENV,
    POLICY_EVENTS,
    TIMING_EVENTS,
    MetricsRegistry,
    MetricsServer,
    emit_event_async,
    get_event_drain,
    log_error_event,
    log_quorum_event,
    trace_span,
    traced,
)
from torchft_tpu.ops.quantization import (
    compress_bucket,
    decompress_bucket,
    is_compressed_wire,
    resolve_compress_mode,
)
from torchft_tpu.process_group import ProcessGroup, ReduceOp
from torchft_tpu.tracing import TRACE_BUFFER_ENV, SpanRecorder, TraceConfig
from torchft_tpu.work import (
    DummyWork,
    Future,
    FutureWork,
    GradStream,
    Work,
    join_futures,
)

T = TypeVar("T")

logger = logging.getLogger(__name__)

__all__ = ["Manager", "WorldSizeMode", "ExceptionWithTraceback"]

# env-var config knobs (reference: manager.py:74-89)
MANAGER_PORT_ENV = "TORCHFT_MANAGER_PORT"
LIGHTHOUSE_ENV = "TORCHFT_LIGHTHOUSE"
# optional pod-level lighthouse aggregator (two-level control plane); the
# manager prefers it for heartbeat/quorum and fails over to the root
# lighthouse on its own if it dies (coordination.AggregatorServer)
AGGREGATOR_ENV = "TORCHFT_LIGHTHOUSE_AGGREGATOR"
TIMEOUT_SEC_ENV = "TORCHFT_TIMEOUT_SEC"
QUORUM_TIMEOUT_SEC_ENV = "TORCHFT_QUORUM_TIMEOUT_SEC"
CONNECT_TIMEOUT_SEC_ENV = "TORCHFT_CONNECT_TIMEOUT_SEC"
QUORUM_RETRIES_ENV = "TORCHFT_QUORUM_RETRIES"
# bucket cap for the managed allreduce's bucketed path, in MiB; 0 disables
# bucketing entirely (per-leaf collectives, the pre-bucketing behavior)
BUCKET_CAP_MB_ENV = "TORCHFT_BUCKET_CAP_MB"
# per-bucket streaming pipeline for the bucketed allreduce: "0"/"false"
# forces the serial monolithic path (pack all → one collective → unpack all)
STREAM_BUCKETS_ENV = "TORCHFT_STREAM_BUCKETS"
# wire compression for streamed buckets ("off" | "fp8" | "int8"): resolved
# in ops/quantization.resolve_compress_mode (env TORCHFT_COMPRESS >
# constructor > "off") so doctor.py validates the same way the Manager does

# timings() keys that are cumulative counters (rendered as Prometheus
# `_total` counters by _refresh_metrics); every other numeric key is a
# last-value gauge
_COUNTER_TIMINGS = frozenset(
    {
        "heal_attempts",
        "heal_failovers",
        "rpc_retries",
        "chunk_crc_failures",
        "collective_reroute",
        "ejections",
        "readmissions",
        "dropped_events",
        "trace_dropped",
        # standby snapshot refused because this replica is itself mid-heal
        # (see _async_quorum_body): a fallback peer asking us for state
        # would get the stale pre-heal copy, so we decline loudly
        "standby_skipped",
        # redundancy plane (redundancy.py): shard staging + reconstruct
        "shards_staged",
        "shard_stage_skipped",
        "shard_stage_dropped",
        "shard_stage_failed",
        "shard_put_failed",
        "shard_announce_rejected",
        "reconstructs",
        "reconstruct_failures",
        "shard_corrupt",
        "shard_fetch_failed",
        # degrade plane (parallel/degrade.py): in-place group shrinks and
        # full-degree restores
        "degrade_events",
        "restored_events",
        # policy plane (_poll_policy_safe_point): frames enforced /
        # observed at the quorum safe point (policy_seq stays a gauge —
        # it is the latest frame version, not a count)
        "policy_applies",
        "policy_intents",
    }
)


def _to_seconds(t: "float | timedelta") -> float:
    return t.total_seconds() if isinstance(t, timedelta) else float(t)


class WorldSizeMode(Enum):
    """Gradient semantics under a changing world size
    (reference: manager.py:123-139).

    DYNAMIC: quorum can be any size >= min_replica_size; batch size varies.
    FIXED_WITH_SPARES: at most min_replica_size replicas contribute; extras
    are hot spares with zeroed contributions, keeping gradient scale fixed.
    """

    DYNAMIC = "dynamic"
    FIXED_WITH_SPARES = "fixed_with_spares"


class ExceptionWithTraceback(Exception):
    def __init__(self, e: Exception) -> None:
        self.original_exception = e
        self.tb = traceback.format_exception(type(e), e, e.__traceback__)
        super().__init__("".join(self.tb))


class _ManagerLogger:
    def __init__(self, manager: "Manager", replica_id: str, group_rank: int):
        self._logger = logger
        self._replica_id = replica_id
        self._group_rank = group_rank
        self._manager = manager

    def _prefix(self) -> str:
        return f"[{self._replica_id}/{self._group_rank} - step {self._manager._step}]"

    def debug(self, msg: str) -> None:
        logger.debug(f"{self._prefix()} {msg}")

    def info(self, msg: str) -> None:
        self._logger.info(f"{self._prefix()} {msg}")

    def warning(self, msg: str) -> None:
        self._logger.warning(f"{self._prefix()} {msg}")

    def exception(self, msg: str) -> None:
        self._logger.exception(f"{self._prefix()} {msg}")


class Manager:
    """Fault-tolerance manager for one worker of one replica group.

    Typical single-process-per-replica-group usage::

        manager = Manager(
            pg=ProcessGroupHost(),
            load_state_dict=load_fn,     # applied on live recovery
            state_dict=state_fn,         # served to healing peers
            min_replica_size=2,
        )
        for batch in data:
            manager.start_quorum()
            grads = grad_fn(params, batch)
            reduced = manager.allreduce(grads).get_future().wait()
            if manager.should_commit():
                params = apply(params, reduced)
    """

    def __init__(
        self,
        pg: ProcessGroup,
        load_state_dict: Optional[Callable[[Any], None]],
        state_dict: Optional[Callable[[], Any]],
        min_replica_size: int,
        use_async_quorum: bool = True,
        timeout: "float | timedelta" = 60.0,
        quorum_timeout: "float | timedelta | None" = None,
        connect_timeout: "float | timedelta | None" = None,
        replica_id: Optional[str] = None,
        lighthouse_addr: Optional[str] = None,
        store_addr: Optional[str] = None,
        group_rank: int = 0,
        group_world_size: int = 1,
        checkpoint_transport: Optional[CheckpointTransport] = None,
        init_sync: bool = True,
        world_size_mode: WorldSizeMode = WorldSizeMode.DYNAMIC,
        max_retries: Optional[int] = None,
        quorum_retries: Optional[int] = None,
        heartbeat_interval: "float | timedelta" = 0.1,
        hostname: str = "",
        bucket_cap_bytes: Optional[int] = None,
        stream_buckets: Optional[bool] = None,
        compress: Optional[str] = None,
        tracing: Optional[bool] = None,
        metrics_port: Optional[int] = None,
        spare: bool = False,
    ) -> None:
        self._pg = pg
        self._min_replica_size = min_replica_size
        self._use_async_quorum = use_async_quorum
        # the mode the CALLER asked for: the requires_sync_quorum override
        # below is re-evaluated per step (start_quorum) so an auto-mode PG
        # that stops requiring sync quorum once configure resolves its mode
        # gets async quorum back — but never a caller who chose sync
        self._requested_async_quorum = use_async_quorum
        if use_async_quorum and getattr(pg, "requires_sync_quorum", False):
            # Safety valve for PGs WITHOUT a prepare/commit configure
            # split that still rebuild global device state inside
            # configure: running that concurrently with the trainer's own
            # jax computations would race backend init mid-rebuild.
            # ProcessGroupXLA no longer sets this — its prepare_configure
            # stages the control plane on the quorum thread and hands the
            # backend swap back as a commit this Manager applies from the
            # main thread (_commit_pending_configure), so async quorum is
            # safe on the device plane.
            logger.info(
                "pg %s requires sync quorum; overriding use_async_quorum",
                type(pg).__name__,
            )
            self._use_async_quorum = False
        self._timeout = float(os.environ.get(TIMEOUT_SEC_ENV, _to_seconds(timeout)))
        self._quorum_timeout = float(
            os.environ.get(
                QUORUM_TIMEOUT_SEC_ENV,
                _to_seconds(quorum_timeout) if quorum_timeout is not None else self._timeout,
            )
        )
        self._connect_timeout = float(
            os.environ.get(
                CONNECT_TIMEOUT_SEC_ENV,
                _to_seconds(connect_timeout) if connect_timeout is not None else 10.0,
            )
        )
        self._replica_world_size_mode = world_size_mode
        self._init_sync = init_sync
        self._max_retries = max_retries
        self._group_rank = group_rank
        self._group_world_size = group_world_size
        quorum_retries = (
            int(os.environ.get(QUORUM_RETRIES_ENV, 0))
            if quorum_retries is None
            else quorum_retries
        )

        # (transport constructed after the hostname default below)

        # user state-dict functions, guarded against concurrent mutation
        # during checkpoint serving (reference: manager.py:243, 366-391)
        self._state_dict_lock = RWLock(timeout=self._timeout)
        self._load_state_dict_fns: Dict[str, Callable[[Any], None]] = {}
        self._user_state_dicts: Dict[str, Callable[[], Any]] = {}
        if state_dict is not None and load_state_dict is not None:
            self.register_state_dict_fn("default", load_state_dict, state_dict)

        self._store: Optional[KvStoreServer] = None
        self._manager: Optional[ManagerServer] = None
        hostname = hostname or _socket.gethostname()

        if checkpoint_transport is None:
            # the heal URL must use the same peer-resolvable hostname the
            # store/manager addresses use, or healing alone breaks on
            # fleets where gethostname() doesn't resolve (k8s pods)
            checkpoint_transport = HTTPTransport(
                timeout=self._timeout, hostname=hostname
            )
        self._checkpoint_transport: CheckpointTransport = checkpoint_transport

        # Hot-spare role (redundancy.py, docs/operations.md): a spare
        # shadows the fleet WITHOUT joining the quorum — no ManagerServer,
        # no lighthouse heartbeat — so the quorum never counts or waits on
        # it. The control-plane join is deferred into promote(), which
        # fires when the shard directory promotes this spare to replace a
        # dead member; until then the quorum-facing methods (start_quorum,
        # should_commit, allreduce) must not be called.
        self._spare = spare
        self._spare_join_args: Optional[Dict[str, Any]] = None
        self._spare_promotion: Optional[Dict[str, Any]] = None
        manager_addr: Optional[str] = None
        if spare:
            if group_rank != 0:
                raise ValueError(
                    "Manager(spare=True) is a whole-replica role: only "
                    "group_rank 0 may construct it"
                )
            replica_name = replica_id if replica_id is not None else "spare"
            self._replica_id = f"{replica_name}:{uuid.uuid4()}"
            self._spare_join_args = {
                "hostname": hostname,
                "store_addr": store_addr,
                "lighthouse_addr": (
                    lighthouse_addr
                    if lighthouse_addr is not None
                    else os.environ.get(LIGHTHOUSE_ENV)
                ),
                "group_world_size": group_world_size,
                "heartbeat_interval": heartbeat_interval,
                "quorum_retries": quorum_retries,
            }
        elif group_rank == 0:
            # Group leader: owns the rendezvous store and the manager server.
            if store_addr is None:
                bind_port = int(os.environ.get(MANAGER_PORT_ENV, 0))
                self._store = KvStoreServer("0.0.0.0:0")
                store_addr = f"{hostname}:{self._store.port}"
            else:
                bind_port = int(os.environ.get(MANAGER_PORT_ENV, 0))

            if lighthouse_addr is None:
                lighthouse_addr = os.environ[LIGHTHOUSE_ENV]

            replica_name = replica_id if replica_id is not None else "replica"
            full_replica_id = f"{replica_name}:{uuid.uuid4()}"
            self._manager = ManagerServer(
                replica_id=full_replica_id,
                lighthouse_addr=lighthouse_addr,
                hostname=hostname,
                bind=f"0.0.0.0:{bind_port}",
                store_addr=store_addr,
                world_size=group_world_size,
                heartbeat_interval=heartbeat_interval,
                connect_timeout=self._connect_timeout,
                quorum_retries=quorum_retries,
                aggregator_addr=os.environ.get(AGGREGATOR_ENV, ""),
            )
            self._replica_id = full_replica_id
            manager_addr = self._manager.address()
            # publish for the other group ranks (reference: manager.py:333-337)
            KvClient(store_addr, connect_timeout=self._connect_timeout).set(
                "manager_addr", manager_addr, timeout=self._timeout
            )
        else:
            assert store_addr is not None, "non-leader ranks need store_addr"
            manager_addr = (
                KvClient(store_addr, connect_timeout=self._connect_timeout)
                .get("manager_addr", timeout=self._timeout)
                .decode()
            )
            self._replica_id = replica_id if replica_id is not None else "replica"

        self._store_addr = store_addr
        self._client: Optional[ManagerClient] = None
        self._vote_client: Optional[ManagerClient] = None
        if manager_addr is not None:
            self._client = ManagerClient(
                manager_addr, connect_timeout=self._connect_timeout
            )
            # Dedicated client for the per-step commit vote: the native RPC
            # client keeps ONE cached keep-alive connection per handle, and a
            # call that arrives while another thread holds it falls back to a
            # one-shot connect. The quorum thread's RPC is in flight exactly
            # when the main thread votes (async quorum), so sharing a handle
            # would put a TCP connect on the hot path every overlapped step.
            self._vote_client = ManagerClient(
                manager_addr, connect_timeout=self._connect_timeout
            )

        # bucketed managed allreduce: cap resolution order is env var >
        # constructor > default; 0 disables (per-leaf collectives)
        env_cap = os.environ.get(BUCKET_CAP_MB_ENV)
        if env_cap is not None:
            self._bucket_cap_bytes = int(float(env_cap) * 1024 * 1024)
        elif bucket_cap_bytes is not None:
            self._bucket_cap_bytes = int(bucket_cap_bytes)
        else:
            self._bucket_cap_bytes = bucketing.DEFAULT_BUCKET_CAP_BYTES
        # host staging buffers recycle through the pool instead of
        # allocating a gradient-sized buffer per step
        self._buffer_pool = bucketing.BufferPool()
        # streaming bucket pipeline: env var > constructor > default ON.
        # Off means the pre-pipeline behavior: one monolithic collective
        # per plan, unpacked only after the LAST bucket's wire completes.
        env_stream = os.environ.get(STREAM_BUCKETS_ENV)
        if env_stream is not None:
            self._stream_buckets = env_stream.strip().lower() not in (
                "0",
                "false",
                "no",
                "off",
            )
        elif stream_buckets is not None:
            self._stream_buckets = bool(stream_buckets)
        else:
            self._stream_buckets = True
        # wire compression for streamed buckets: TORCHFT_COMPRESS env >
        # constructor > "off". Raises on a bad value (same message the
        # doctor check surfaces) rather than training uncompressed silently.
        self._compress = resolve_compress_mode(compress)
        # per-(plan, bucket) error-feedback residuals: what quantization
        # rounded away this step is added back before quantizing the next
        # step, so the compression error stays bounded instead of
        # accumulating (LocalSGD/DiLoCo convergence depends on this).
        # Keyed by plan identity via weakref so evicted plans drop their
        # residual buffers with them; buffers come from the BufferPool.
        self._ef_residuals: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )
        self._ef_lock = threading.Lock()

        self._step = 0
        self._quorum_id = -1
        self._batches_committed = 0
        self._commit_failures = 0
        self._errored: Optional[ExceptionWithTraceback] = None
        # lifetime counters for metrics() — monotonic, never reset (unlike
        # _commit_failures, which is the protocol's CONSECUTIVE counter)
        self._metrics_lock = threading.Lock()
        self._metrics: Dict[str, int] = {
            "quorums": 0,
            "reconfigures": 0,
            "heals": 0,
            "commits": 0,
            "commit_failures": 0,
            "allreduces": 0,
            "errors": 0,
        }
        self._healing = False
        self._last_quorum_healed = False
        # True while this replica holds a standby failover snapshot open
        # for a heal in progress elsewhere in the quorum (see
        # _async_quorum_body); should_commit defers disallow_checkpoint
        # until the episode ends
        self._standby_source = False
        self._pending_state_dict: Optional[Dict[str, Any]] = None
        # prepare/commit configure split: the quorum thread stages the
        # reconfigure (prepare_configure) and stashes the returned commit
        # here; the main thread applies it at the next safe point via
        # _commit_pending_configure. Guarded by its own lock so a late
        # quorum-thread stash can't race the main-thread take.
        self._pending_pg_commit: Optional[Callable[[], None]] = None
        self._pending_commit_lock = threading.Lock()
        # per-phase wall-clock timings for the most recent quorum cycle
        # (quorum_overlap_s, configure_prepare_s, configure_commit_s,
        # heal_recv_s, ...) — shares _metrics_lock
        self._timings: Dict[str, float] = {}
        # resilience counters ride the same dict so they flow through
        # timings() and the torchft_timings stream without a second
        # plumbing path. Unlike the phase timings these are CUMULATIVE:
        # a blip that cost two RPC retries three steps ago stays visible.
        for _counter in (
            "heal_attempts",
            "heal_failovers",
            "rpc_retries",
            "chunk_crc_failures",
            "collective_reroute",
            "standby_skipped",
        ):
            self._timings[_counter] = 0.0
        # rpc_retries: every retried control-plane call on either manager
        # client bumps the counter and leaves a flight-recorder breadcrumb,
        # so "the step got slower" is attributable to a named RPC.
        # (A spare has no clients until promote() joins the control plane.)
        if self._client is not None:
            self._client.set_retry_observer(self._on_rpc_retry)
        if self._vote_client is not None:
            self._vote_client.set_retry_observer(self._on_rpc_retry)
        # collective_reroute: the compressed ring re-formed around a dead
        # link mid-collective. Same pattern as rpc_retries — counter plus a
        # flight-recorder breadcrumb naming the link.
        _set_reroute = getattr(pg, "set_reroute_observer", None)
        if _set_reroute is not None:
            _set_reroute(self._on_collective_reroute)
        # healthwatch: the group leader piggybacks per-step telemetry on
        # its heartbeat thread (publish_telemetry) and reads the
        # lighthouse's health summary back off the same round-trip. The
        # summary's cumulative counters and latest state ride timings();
        # state TRANSITIONS additionally emit torchft_health events and
        # flight-recorder breadcrumbs (_publish_step_telemetry).
        for _counter in ("health_state", "straggler_score", "ejections", "readmissions"):
            self._timings[_counter] = 0.0
        # degrade plane: in-place group shrinks / full-degree restores
        # (docs/operations.md#degraded-replicas)
        for _counter in ("degrade_events", "restored_events"):
            self._timings[_counter] = 0.0
        # policy plane (docs/operations.md#adaptive-policies): frames are
        # polled off the heartbeat mirror at the start_quorum safe point.
        # policy_seq = last frame version seen; policy_intents counts
        # observe-mode would-be applications, policy_applies enforce-mode
        # real ones. TORCHFT_POLICY=off skips the poll entirely (the
        # byte-identical contract pinned by test_policy_off_byte_identical).
        for _counter in ("policy_seq", "policy_applies", "policy_intents"):
            self._timings[_counter] = 0.0
        self._policy_mode = knobs.env_str("TORCHFT_POLICY", "off").strip() or "off"
        self._policy_seq_seen = -1
        # live knob adjusters: knob name -> setter, registered by the
        # planes that can retarget without a restart (LocalSGD/DiLoCo
        # sync_every, redundancy staging interval). Applied in enforce
        # mode at the safe point, after knobs.set_override.
        self._policy_adjusters: Dict[str, Callable[[str], None]] = {}
        # the override set THIS manager last applied in enforce mode —
        # the diff base for reverts (knobs' global layer is shared
        # across managers in-process, so it can't be the baseline)
        self._policy_overrides_applied: Dict[str, str] = {}
        self._telemetry_transform: Optional[
            Callable[[Dict[str, Any]], Dict[str, Any]]
        ] = None
        self._last_health_state: Optional[str] = None
        self._last_commit_t: Optional[float] = None
        # serving plane (attach_serve_publisher): committed snapshots are
        # published as (quorum_id, step) versions; None = plane disabled
        self._serve_publisher: Optional[Any] = None
        self._serve_params_fn: Optional[Callable[[], Any]] = None
        self._last_vote_committed = False
        self._telemetry_quorum_id: Optional[int] = None
        self._participating_replica_rank: Optional[int] = None
        # last seen PG backend generation (see _sync_device_world)
        self._device_world_epoch = getattr(pg, "device_world_epoch", None)
        self._participating_replica_world_size: int = 0
        self._num_replicas: int = 0

        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="torchft_quorum"
        )
        # one ordered worker for host-plane allreduce staging: D2H + wire
        # dispatch off the train loop, issue order preserved across replicas
        self._staging_executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="torchft_stage"
        )
        # pipeline stage 3: per-bucket unpack + device landing runs here so
        # it neither blocks the PG's dispatch thread (which would serialize
        # the NEXT bucket's wire behind this bucket's unpack) nor waits for
        # the last bucket's wire like the monolithic path did
        self._unpack_executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="torchft_unpack"
        )
        # (executor future, staged future) pairs still in flight: shutdown
        # must fail the staged futures of cancelled tasks or their waiters
        # stall for the full timeout. Guarded together with the shutdown
        # flag so a submit can't race the shutdown sweep.
        self._staged_pending: List[Any] = []
        self._staged_lock = threading.Lock()
        self._staging_down = False
        self._quorum_future: Optional[Any] = None

        self._logger = _ManagerLogger(self, self._replica_id, group_rank)

        # fleet tracing plane: per-replica span recorder (tracing.py).
        # Constructor arg > TORCHFT_TRACE env (default on); spans are O(1)
        # dict appends behind one lock, so the default-on cost holds the
        # bench.py --tracing <1% line.
        trace_cfg = TraceConfig.from_env()
        if tracing is not None:
            trace_cfg.enabled = bool(tracing)
        self._tracer = SpanRecorder(self._replica_id, trace_cfg)
        # one-shot latch for the dropped_events warning (satellite: the
        # drain's drop count used to be silently discarded)
        self._dropped_events_warned = False

        # manager-side /metrics: constructor arg > TORCHFT_METRICS_PORT
        # env; absent/empty = no server. Histograms are fed at record time
        # (_record_timing); gauges/counters sync from timings() and
        # wire_stats() only when a scrape actually arrives (refresh hook).
        self._metrics_registry: Optional[MetricsRegistry] = None
        self._metrics_server: Optional[MetricsServer] = None
        env_metrics = os.environ.get(METRICS_PORT_ENV, "")
        if metrics_port is None and env_metrics != "":
            try:
                metrics_port = int(env_metrics)
            except ValueError:
                self._logger.warning(
                    f"ignoring invalid {METRICS_PORT_ENV}={env_metrics!r}"
                )
        if metrics_port is not None:
            # Never let the observability knob take down training: with a
            # fixed TORCHFT_METRICS_PORT and >1 Manager per host (multiple
            # group ranks, or a restart racing TIME_WAIT) the bind raises
            # EADDRINUSE — run without metrics instead of crashing.
            try:
                registry = MetricsRegistry()
                self._metrics_server = MetricsServer(
                    registry,
                    port=metrics_port,
                    refresh=self._refresh_metrics,
                )
                self._metrics_registry = registry
            except OSError as e:
                self._logger.warning(
                    f"metrics server failed to bind port {metrics_port} "
                    f"({e}); continuing without /metrics"
                )

        # redundancy plane (redundancy.py, docs/operations.md): when
        # TORCHFT_REDUNDANCY_K >= 1 and a shard directory is configured,
        # the group leader erasure-codes every committed generation and
        # stages the shards across peers off the hot path, and the heal
        # path tries a parallel reconstruct before the serial peer pull.
        # k=0 (the default) leaves every existing path byte-identical —
        # pinned by tests/test_redundancy.py.
        self._redundancy_cfg: Optional[Any] = None
        self._shard_stager: Optional[Any] = None
        self._hot_spare: Optional[Any] = None
        self._redundancy_stage_pending = False
        try:
            from torchft_tpu import redundancy as _redundancy

            _red_cfg = _redundancy.RedundancyConfig.from_env()
            if spare:
                if not _red_cfg.directory:
                    raise ValueError(
                        "Manager(spare=True) requires a shard directory "
                        f"(${_redundancy.REDUNDANCY_DIRECTORY_ENV})"
                    )
                self._redundancy_cfg = _red_cfg
                self._hot_spare = _redundancy.HotSpare(
                    _red_cfg,
                    spare_id=self._replica_id,
                    # shadow the serving-plane delta chain too when the
                    # registry is configured (serving.SERVE_REGISTRY_ENV)
                    serve_registry=os.environ.get(
                        "TORCHFT_SERVE_REGISTRY", ""
                    )
                    or None,
                    on_metric=self._on_redundancy_metric,
                )
            elif _red_cfg.enabled:
                _red_cfg.validate()
                self._redundancy_cfg = _red_cfg
                if group_rank == 0:
                    self._shard_stager = _redundancy.ShardStager(
                        _red_cfg,
                        self._replica_id,
                        on_metric=self._on_redundancy_metric,
                    )
        except ValueError:
            raise
        except Exception:  # noqa: BLE001 — the plane is advisory
            self._logger.exception(
                "redundancy plane failed to attach; continuing without it"
            )
            self._redundancy_cfg = None
            self._shard_stager = None
        if self._shard_stager is not None:
            # policy plane can retune staging cadence / parity count live;
            # both take effect at the next maybe_stage (per-commit gate)
            self._policy_red_defaults = (
                self._redundancy_cfg.interval,
                self._redundancy_cfg.m,
            )
            self.register_policy_adjuster(
                "TORCHFT_REDUNDANCY_INTERVAL", self._policy_set_red_interval
            )
            self.register_policy_adjuster(
                "TORCHFT_REDUNDANCY_M", self._policy_set_red_m
            )

        # degrade plane (parallel/degrade.py, docs/operations.md
        # #degraded-replicas): with TORCHFT_DEGRADE=on a dead chip inside
        # the replica group shrinks the group's own TP/PP degree in place
        # — a re-planned slow step — instead of costing the whole group a
        # leave-heal-rejoin cycle. Off (the default) registers nothing and
        # leaves every code path byte-identical, pinned by
        # tests/test_degrade.py.
        self._degrade_cfg: Optional[Any] = None
        self._degrade_lock = threading.Lock()
        # the group's parallel degree: in single-controller SPMD jobs the
        # mesh spans chips the Manager's group_world_size never sees, so
        # the degree is declared via set_group_degree()
        self._full_group_degree: int = group_world_size
        self._group_degree: int = group_world_size
        self._degrade_pending: Optional[int] = None  # dead group_rank
        self._reshard_fn: Optional[Callable[[int, int], Any]] = None
        try:
            from torchft_tpu.parallel.degrade import DegradeConfig

            _deg_cfg = DegradeConfig.from_env()
            if _deg_cfg.enabled:
                self._degrade_cfg = _deg_cfg
                # member-death detection: the abort watchdog / fault
                # injection path on PGs that track intra-group members.
                # Registered ONLY when the plane is on.
                _set_death = getattr(pg, "set_member_death_callback", None)
                if _set_death is not None:
                    _set_death(self.report_member_death)
        except ValueError:
            raise
        except Exception:  # noqa: BLE001 — the plane is advisory
            self._logger.exception(
                "degrade plane failed to attach; continuing without it"
            )
            self._degrade_cfg = None

    # ------------------------------------------------------------- state fns
    def register_state_dict_fn(
        self,
        key: str,
        load_fn: Callable[[Any], None],
        value_fn: Callable[[], Any],
    ) -> None:
        """Register a named (load, save) pair included in live recovery
        (reference: manager.py:380-391)."""
        with self._state_dict_lock.w_lock():
            self._load_state_dict_fns[key] = load_fn
            self._user_state_dicts[key] = value_fn

    def set_state_dict_fns(
        self,
        load_state_dict: Callable[[Any], None],
        state_dict: Callable[[], Any],
    ) -> None:
        """Deprecated alias kept for reference API parity
        (manager.py set_state_dict_fns); use register_state_dict_fn."""
        self._logger.warning(
            "set_state_dict_fns is deprecated, use register_state_dict_fn"
        )
        # Register under "default" (the constructor's slot) so a replica using
        # this legacy setter stays checkpoint-compatible when healing from a
        # replica that registered via the constructor, and vice versa.
        self.register_state_dict_fn("default", load_state_dict, state_dict)

    def allow_state_dict_read(self) -> None:
        if self._state_dict_lock.w_locked():
            self._state_dict_lock.w_release()

    def disallow_state_dict_read(self) -> None:
        if not self._state_dict_lock.w_locked():
            self._state_dict_lock.w_acquire()

    # --------------------------------------------------------------- quorum
    def start_quorum(
        self,
        allow_heal: bool = True,
        shrink_only: bool = False,
        timeout: "float | timedelta | None" = None,
    ) -> None:
        """Compute a new quorum (async by default) and ready the manager for a
        new step. Call before the forward pass (reference: manager.py:560-615)."""
        if self._quorum_future is not None:
            self._quorum_future.result()
            # a commit left over from the previous quorum (e.g. the caller
            # skipped should_commit after an error) must land before the
            # next prepare runs against the old world
            self._commit_pending_configure()

        # Re-evaluate the construction-time sync-quorum override: an
        # auto-mode PG can't know whether it needs sync quorum until its
        # first configure resolves the mode, so sampling the property once
        # at construction would tax every later step with a synchronous
        # quorum RPC. Only the caller's requested mode is ever restored.
        if (
            self._requested_async_quorum
            and not self._use_async_quorum
            and not getattr(self._pg, "requires_sync_quorum", False)
        ):
            self._logger.info(
                "pg no longer requires sync quorum; restoring async quorum"
            )
            self._use_async_quorum = True

        if self._shard_stager is not None and self._redundancy_stage_pending:
            # redundancy plane: the previous round committed and the
            # caller has applied its update — the user state is now the
            # exact post-commit generation a healer joining THIS round
            # needs, and announcing before the quorum/allreduce barrier
            # means that healer can reconstruct it instead of deadlocking
            # on a commit it is itself blocking
            self._redundancy_stage_pending = False
            self._stage_redundancy_committed()

        self._errored = None
        self._healing = False
        self._last_quorum_healed = False

        # a degrade staged since the last safe point lands here, AFTER the
        # per-step error reset and BEFORE the new prepare is submitted: the
        # reshard must replace the dead member before the next quorum's
        # world is staged, and a fallback's report_error must survive into
        # this step so its vote fails (placing this above the reset
        # silently swallowed the fallback)
        if self._degrade_cfg is not None:
            self._commit_pending_degrade()

        # adaptive policy plane: a frame that arrived on the heartbeat
        # mirror lands here — the quorum safe point — never mid-step.
        # TORCHFT_POLICY=off skips the poll entirely (byte-identical).
        if self._policy_mode != "off":
            self._poll_policy_safe_point()

        self._quorum_future = self._executor.submit(
            self._async_quorum,
            allow_heal=allow_heal,
            shrink_only=shrink_only,
            quorum_timeout=_to_seconds(timeout) if timeout is not None else self._quorum_timeout,
        )
        if not self._use_async_quorum:
            self.wait_quorum()
            self._commit_pending_configure()
            self._sync_device_world()
            if self._healing and self._pending_state_dict is not None:
                # apply eagerly so the forward pass runs on recovered state
                self._apply_pending_state_dict()
                self._healing = False
            elif self._healing:
                # recovery failed (error already reported); retry next quorum
                self._healing = False

    def wait_quorum(self) -> None:
        assert self._quorum_future is not None, "must call start_quorum first"
        with trace_span("torchft::manager::wait_quorum"):
            self._quorum_future.result()

    # ------------------------------------------------------------- policy
    def register_policy_adjuster(
        self, knob: str, fn: "Callable[[Optional[str]], None]"
    ) -> None:
        """Register a live setter for one knob (LocalSGD/DiLoCo register
        their ``sync_every`` here, redundancy its staging interval). In
        enforce mode the setter runs at the quorum safe point with the
        frame's string value, or ``None`` when the override is released
        (hysteresis relaxed) — the plane restores its construction-time
        value. Last registration per knob wins."""
        self._policy_adjusters[knob] = fn

    def policy_status(self) -> Dict[str, Any]:
        """Operator view of the policy plane on this replica: mode, last
        frame seq applied/observed, and the override set in force."""
        with self._metrics_lock:
            seq = int(self._timings.get("policy_seq", 0.0))
        return {
            "mode": self._policy_mode,
            "policy_seq": seq,
            "overrides": knobs.get_overrides(),
            "adjusters": sorted(self._policy_adjusters),
        }

    def _policy_set_red_interval(self, value: Optional[str]) -> None:
        cfg = self._redundancy_cfg
        if cfg is None:
            return
        if value is None:
            cfg.interval = self._policy_red_defaults[0]
        else:
            cfg.interval = max(1, int(value))

    def _policy_set_red_m(self, value: Optional[str]) -> None:
        cfg = self._redundancy_cfg
        if cfg is None:
            return
        if value is None:
            m = self._policy_red_defaults[1]
        else:
            # keep within the GF(256) shard limit the constructor enforces
            m = min(max(1, int(value)), 255 - cfg.k)
        cfg.m = m

    def _poll_policy_safe_point(self) -> None:
        """Poll the heartbeat mirror for a new policy frame and act on it.

        Runs only from start_quorum (the safe point: no collective in
        flight, the previous configure committed) and only when
        TORCHFT_POLICY != off. Observe mode records the would-be action
        everywhere an operator looks (timings, torchft_policy stream,
        flight recorder, trace instant) without touching a knob; enforce
        additionally installs the overrides through the central registry
        layer and runs the registered live adjusters. Must never raise —
        a malformed frame degrades to a logged warning, not a lost step."""
        try:
            frame = self._manager.policy() if self._manager is not None else {}
        except Exception:  # noqa: BLE001 — mirror read must not cost a step
            return
        if not frame:
            return
        try:
            seq = int(frame.get("policy_seq", 0))
            if seq <= self._policy_seq_seen:
                return
            self._policy_seq_seen = seq
            overrides = {
                str(k): str(v)
                for k, v in (frame.get("knob_overrides") or {}).items()
                if knobs.is_registered(str(k))
            }
            enforce = (
                self._policy_mode == "enforce"
                and str(frame.get("mode", "")) == "enforce"
            )
            with self._metrics_lock:
                self._timings["policy_seq"] = float(seq)
                if enforce:
                    self._timings["policy_applies"] += 1.0
                else:
                    self._timings["policy_intents"] += 1.0
            action = "apply" if enforce else "intent"
            self._logger.info(
                f"policy: {action} seq={seq} overrides={overrides} "
                f"rules={frame.get('active_rules', [])}"
            )
            emit_event_async(
                POLICY_EVENTS,
                replica_id=self._replica_id,
                group_rank=self._group_rank,
                step=self._step,
                quorum_id=self._quorum_id,
                policy_seq=seq,
                action=action,
                overrides=overrides,
                active_rules=list(frame.get("active_rules", [])),
            )
            from torchft_tpu.flight_recorder import recorder

            recorder.record(
                "policy_" + action,
                policy_seq=seq,
                overrides=overrides,
                step=self._step,
                replica=self._replica_id,
            )
            self._tracer.instant(
                "policy_" + action, cat="policy", policy_seq=seq
            )
            if not enforce:
                return
            # Enforce: diff against what THIS manager applied from the
            # predecessor frame so a released rule's knob reverts
            # (hysteresis relaxation must undo, not just stop
            # re-applying). The diff base is per-manager, not the global
            # override layer: with several managers in one process (test
            # fleets) whichever polls a frame first mutates the shared
            # layer, and diffing against it would skip the others'
            # adjuster restore calls.
            previous = self._policy_overrides_applied
            for name in previous:
                if name not in overrides:
                    knobs.set_override(name, None)
                    adjuster = self._policy_adjusters.get(name)
                    if adjuster is not None:
                        adjuster(None)
            for name, value in overrides.items():
                knobs.set_override(name, value)
                adjuster = self._policy_adjusters.get(name)
                if adjuster is not None:
                    adjuster(value)
            # Manager-owned knob: the wire codec retargets in place (the
            # next streamed allreduce picks it up; error-feedback
            # residuals are keyed per plan and survive the switch).
            if "TORCHFT_COMPRESS" in overrides:
                self._compress = resolve_compress_mode(
                    overrides["TORCHFT_COMPRESS"]
                )
            elif "TORCHFT_COMPRESS" in previous:
                self._compress = resolve_compress_mode(None)
            self._policy_overrides_applied = dict(overrides)
        except Exception:  # noqa: BLE001
            self._logger.exception("policy frame handling failed (ignored)")

    def _sync_device_world(self) -> None:
        """Re-land registered user state on the live jax backend after the
        PG rebuilt the device world (ProcessGroupXLA's per-quorum
        distributed worlds tear down + rejoin `jax.distributed`). Arrays
        created on the OLD backend stay readable but cannot mix with
        new-world arrays inside one jitted computation — without this, the
        first post-reconfigure optimizer update dies with "incompatible
        devices for jitted computation". Called from the main thread at
        the should_commit / start_quorum sync points (the same places a
        pending heal is applied). No-op for PGs without a
        ``device_world_epoch`` (host PGs, local mode) and when a pending
        heal is about to overwrite user state anyway."""
        epoch = getattr(self._pg, "device_world_epoch", None)
        if epoch is None or epoch == self._device_world_epoch:
            return
        self._device_world_epoch = epoch
        if self._healing and self._pending_state_dict is not None:
            return  # the heal lands on the live backend and wins
        if not self._user_state_dicts:
            return
        import jax

        self._logger.info(
            f"device world rebuilt (epoch {epoch}); re-landing user state "
            "on the live backend"
        )
        host = jax.tree_util.tree_map(
            lambda l: np.asarray(l) if isinstance(l, jax.Array) else l,
            self.user_state_dict(),
        )
        self.load_user_state_dict(host)

    @traced("torchft::manager::_async_quorum")
    def _async_quorum(
        self, allow_heal: bool, shrink_only: bool, quorum_timeout: float
    ) -> None:
        # quorum_overlap_s is the wall-clock the whole control-plane cycle
        # spent on the quorum thread — with async quorum this is the work
        # the train step no longer waits for (minus configure_commit_s,
        # the only piece that still serializes with the trainer)
        t0 = time.perf_counter()
        try:
            self._async_quorum_body(allow_heal, shrink_only, quorum_timeout)
        finally:
            self._record_timing("quorum_overlap_s", time.perf_counter() - t0)

    def _async_quorum_body(
        self, allow_heal: bool, shrink_only: bool, quorum_timeout: float
    ) -> None:
        try:
            with self._tracer.span("quorum_rpc", cat="quorum"):
                quorum = self._client._quorum(
                    group_rank=self._group_rank,
                    step=self._step,
                    checkpoint_metadata=self._checkpoint_transport.metadata(),
                    shrink_only=shrink_only,
                    timeout=quorum_timeout,
                    init_sync=self._init_sync,
                    commit_failures=self._commit_failures,
                )
        except Exception as e:  # noqa: BLE001
            self._logger.exception(f"quorum RPC failed: {e}")
            self.report_error(e)
            return

        self._num_replicas = quorum.replica_world_size
        self._bump_metric("quorums")
        self._tracer.set_context(quorum_id=quorum.quorum_id, step=self._step)

        # Participation (reference: manager.py:671-690): async quorum means
        # healing replicas sit this step out, so the participating world is
        # the max-step cohort; sync quorum heals first, so everyone counts.
        if self._use_async_quorum or not allow_heal:
            self._participating_replica_rank = quorum.max_replica_rank
            self._participating_replica_world_size = quorum.max_world_size
        else:
            self._participating_replica_rank = quorum.replica_rank
            self._participating_replica_world_size = quorum.replica_world_size

        if self._replica_world_size_mode == WorldSizeMode.FIXED_WITH_SPARES:
            # Spares beyond min_replica_size contribute zeros so gradient
            # scale stays constant.
            self._participating_replica_world_size = min(
                self._participating_replica_world_size, self._min_replica_size
            )
            if (
                self._participating_replica_rank is not None
                and self._participating_replica_rank >= self._min_replica_size
            ):
                self._participating_replica_rank = None

        if quorum.quorum_id != self._quorum_id:
            store_prefixed_addr = (
                f"{quorum.store_address}/torchft/{quorum.quorum_id}/{self._group_rank}"
            )
            self._logger.info(
                f"reconfiguring for quorum_id={quorum.quorum_id} store={store_prefixed_addr}"
            )
            log_quorum_event(
                replica_id=self._replica_id,
                group_rank=self._group_rank,
                step=self._step,
                quorum_id=quorum.quorum_id,
                replica_rank=quorum.replica_rank,
                replica_world_size=quorum.replica_world_size,
                heal=quorum.heal,
                recover_dst_replica_ranks=quorum.recover_dst_replica_ranks,
            )
            try:
                self._bump_metric("reconfigures")
                # prepare/commit split: everything control-plane runs HERE
                # on the quorum thread; a PG that must swap live backend
                # state returns that swap as a commit callable which the
                # main thread applies at the next safe point
                t_prep = time.perf_counter()
                with trace_span("torchft::manager::_pg::prepare_configure"), \
                        self._tracer.span("configure_prepare", cat="quorum"):
                    pg_commit = self._pg.prepare_configure(
                        store_prefixed_addr,
                        quorum.replica_rank,
                        quorum.replica_world_size,
                        quorum_id=quorum.quorum_id,
                    )
                self._record_timing(
                    "configure_prepare_s", time.perf_counter() - t_prep
                )
                with self._pending_commit_lock:
                    self._pending_pg_commit = pg_commit
                if pg_commit is None:
                    # fully committed in prepare (host PGs, local mode):
                    # report an explicit zero so BENCH rows always carry
                    # the key and overlap math stays artifact-derivable
                    self._record_timing("configure_commit_s", 0.0)
                # keep the checkpoint transport in lockstep with the quorum
                # (no-op for address-based transports; PGTransport
                # rendezvouses its recovery PG here). Distinct /recovery
                # store namespace so the two meshes can't cross-wire.
                with trace_span("torchft::manager::_transport::configure"), \
                        self._tracer.span(
                            "transport_configure", cat="quorum"
                        ):
                    self._checkpoint_transport.configure(
                        f"{quorum.store_address}/torchft/{quorum.quorum_id}"
                        f"/recovery/{self._group_rank}",
                        quorum.replica_rank,
                        quorum.replica_world_size,
                        quorum_id=quorum.quorum_id,
                    )
                # recorded only after BOTH configures succeed. On failure
                # _quorum_id stays stale and the step's commit vote fails,
                # so the next quorum request carries commit_failures>0 and
                # the lighthouse bumps quorum_id (native/lighthouse.cc) —
                # EVERY replica then re-rendezvouses under the new id.
                # That bump, not a one-sided same-id retry, is what makes
                # the retry collective (a lone replica re-running a
                # blocking mesh rendezvous its peers skipped would just
                # time out); tests/test_manager_integ.py pins the loop.
                self._quorum_id = quorum.quorum_id
                # flight-recorder reconfiguration boundary marker
                # (reference: manager.py:729-733, 808-817)
                from torchft_tpu.flight_recorder import recorder

                recorder.record(
                    "quorum_reconfigure",
                    quorum_id=quorum.quorum_id,
                    replica=self._replica_id,
                    group_rank=self._group_rank,
                )
                if pg_commit is None:
                    # split PGs log theirs from _commit_pending_configure,
                    # after the commit half has a measured duration
                    self._log_timing_snapshot("configure_prepare")
            except Exception as e:  # noqa: BLE001
                self._logger.exception(f"got exception in pg configure: {e}")
                self.report_error(e)
                return

        if allow_heal:
            try:
                if quorum.recover_dst_replica_ranks:
                    self._logger.info(
                        f"peers need recovery from us {quorum.recover_dst_replica_ranks}"
                    )
                    t_send = time.perf_counter()
                    with trace_span("torchft::manager::send_checkpoint"), \
                            self._tracer.span(
                                "heal_send",
                                cat="heal",
                                dst_ranks=list(
                                    quorum.recover_dst_replica_ranks
                                ),
                            ):
                        self._checkpoint_transport.send_checkpoint(
                            dst_ranks=quorum.recover_dst_replica_ranks,
                            step=quorum.max_step,
                            state_dict=self._manager_state_dict(),
                            timeout=self._timeout,
                        )
                    self._record_timing(
                        "heal_send_s", time.perf_counter() - t_send
                    )

                # Standby failover source: someone in the quorum is behind
                # but WE got no dst assignment. A healing replica whose
                # assigned source dies mid-transfer fails over to the
                # fallback peers the quorum computed — which only works if
                # those peers actually have the step staged. Stage once per
                # heal episode (rising edge; the snapshot owns host copies,
                # so serving stays consistent while training mutates live
                # state) and hold the window open across commits until the
                # quorum shows nobody behind (should_commit skips
                # disallow_checkpoint while _standby_source is set).
                # Pull-based transports only: a PGTransport standby would
                # just rendezvous a transfer no one initiates.
                standby_wanted = (
                    not quorum.recover_dst_replica_ranks
                    and quorum.max_world_size < quorum.replica_world_size
                    and self._checkpoint_transport.supports_multi_source
                )
                standby = standby_wanted and not quorum.heal
                if standby_wanted and quorum.heal:
                    # We are a fallback candidate AND behind ourselves: the
                    # quorum listed us as a standby source, but our local
                    # state is the pre-heal copy — serving it would hand a
                    # failing-over peer stale state. Refuse loudly instead
                    # of silently staging nothing (the old behavior left
                    # fallback peers shardless with no audit trail).
                    self._logger.warning(
                        "refusing to stage standby failover snapshot for "
                        f"step {quorum.max_step}: this replica is itself "
                        "mid-heal and holds stale state"
                    )
                    self._bump_counter("standby_skipped")
                if standby and not self._standby_source:
                    self._logger.info(
                        "staging standby failover snapshot for "
                        f"step {quorum.max_step}"
                    )
                    self._checkpoint_transport.send_checkpoint(
                        dst_ranks=[],
                        step=quorum.max_step,
                        state_dict=self._manager_state_dict(),
                        timeout=self._timeout,
                    )
                self._standby_source = standby

                if quorum.heal:
                    self._healing = True
                    assert quorum.recover_src_replica_rank is not None
                    self._bump_counter("heal_attempts")
                    t_recv = time.perf_counter()
                    with trace_span("torchft::manager::recv_checkpoint"), \
                            self._tracer.span("heal_recv", cat="heal"):
                        self._pending_state_dict = self._recv_checkpoint(quorum)
                    self._record_timing(
                        "heal_recv_s", time.perf_counter() - t_recv
                    )
                    stream = self._checkpoint_transport.last_recv_timings()
                    if stream is not None:
                        self._record_timing("heal_chunks", float(stream.num_chunks))
                        self._record_timing("heal_mb_per_s", stream.mb_per_s)
                    # restore ft step/batches immediately; user state is
                    # applied from the main thread when safe
                    self.load_state_dict(self._pending_state_dict["torchft"])
                    self._step = quorum.max_step
            except Exception as e:  # noqa: BLE001
                self._logger.exception(f"got exception in recovery: {e}")
                self.report_error(e)

    # ------------------------------------------------------------- healing
    def _heal_sources(
        self, quorum: Any
    ) -> List[Any]:
        """Ordered candidate sources for a multi-peer heal: the assigned
        recovery source first, then every other up-to-date peer in the
        round-robin order the native quorum computed
        (``recover_src_fallbacks``). Each entry is ``(label, metadata_fn)``
        with the metadata RPC resolved LAZILY — an unreachable fallback
        costs nothing unless the transport actually fails over to it."""

        def _metadata_fn(addr: str) -> Callable[[], str]:
            def fetch() -> str:
                client = ManagerClient(
                    addr, connect_timeout=self._connect_timeout
                )
                # the metadata RPC itself rides the bounded-retry layer,
                # feeding the same rpc_retries counter as the main clients
                client.set_retry_observer(self._on_rpc_retry)
                return client._checkpoint_metadata(
                    self._group_rank, timeout=self._timeout
                )

            return fetch

        sources = [
            (
                f"replica_rank_{quorum.recover_src_replica_rank}"
                f"@{quorum.recover_src_manager_address}",
                _metadata_fn(quorum.recover_src_manager_address),
            )
        ]
        for peer in quorum.recover_src_fallbacks:
            sources.append(
                (
                    f"replica_rank_{peer.replica_rank}@{peer.address}",
                    _metadata_fn(peer.address),
                )
            )
        return sources

    def _on_heal_event(self, kind: str, **fields: Any) -> None:
        """Transport → Manager bridge for resilient-heal notifications:
        bump the matching cumulative counter and leave a flight-recorder
        breadcrumb so a postmortem can reconstruct the heal's retry/
        failover sequence."""
        counter = {
            "heal_retry": "heal_attempts",
            "heal_failover": "heal_failovers",
            "chunk_crc_failure": "chunk_crc_failures",
        }.get(kind)
        if counter is not None:
            self._bump_counter(counter)
        self._tracer.instant(kind, cat="heal", **fields)
        from torchft_tpu.flight_recorder import recorder

        recorder.record(
            kind,
            step=self._step,
            replica=self._replica_id,
            group_rank=self._group_rank,
            **fields,
        )

    def _on_redundancy_metric(self, name: str, value: float) -> None:
        """ShardStager/HotSpare → Manager metrics bridge: counters (named
        in _COUNTER_TIMINGS) accumulate, everything else is a last-value
        gauge riding timings() like any phase timing."""
        if name in _COUNTER_TIMINGS:
            self._bump_counter(name, value)
        else:
            self._record_timing(name, value)

    def _on_redundancy_event(self, kind: str, info: Dict[str, Any]) -> None:
        """reconstruct_state → Manager bridge: per-shard faults become
        cumulative counters + tracer instants so a heal postmortem can say
        WHICH shard failed or arrived corrupt."""
        counter = {
            "shard_corrupt": "shard_corrupt",
            "shard_fetch_failed": "shard_fetch_failed",
        }.get(kind)
        if counter is not None:
            self._bump_counter(counter)
        self._tracer.instant(kind, cat="redundancy", **info)

    def _reconstruct_checkpoint(self, quorum: Any) -> Optional[Dict[str, Any]]:
        """Attempt the parallel shard reconstruct for this heal. Returns
        the state dict on success, None to fall back to the peer pull
        (never raises — the redundancy plane is an accelerator, not a
        dependency, of healing)."""
        from torchft_tpu import redundancy as _redundancy

        cfg = self._redundancy_cfg
        assert cfg is not None
        t0 = time.perf_counter()
        try:
            with self._tracer.span(
                "reconstruct", cat="redundancy", step=quorum.max_step
            ):
                step, state, stats = _redundancy.reconstruct_state(
                    cfg.directory,
                    step=quorum.max_step,
                    timeout=self._timeout,
                    on_event=self._on_redundancy_event,
                )
        except Exception as e:  # noqa: BLE001 — fall back to peer pull
            self._logger.warning(
                f"shard reconstruct unavailable ({e!r}); falling back to "
                "peer heal"
            )
            self._bump_counter("reconstruct_failures")
            return None
        if step != quorum.max_step:
            self._logger.warning(
                f"shard directory generation is step {step}, quorum wants "
                f"{quorum.max_step}; falling back to peer heal"
            )
            self._bump_counter("reconstruct_failures")
            return None
        self._bump_counter("reconstructs")
        self._record_timing(
            "reconstruct_s", stats.get("reconstruct_s", time.perf_counter() - t0)
        )
        self._record_timing(
            "reconstruct_mb_per_s", float(stats.get("mb_per_s", 0.0))
        )
        self._logger.info(
            f"healed step {step} by parallel reconstruct: "
            f"{stats['shards_ok']} shards ok, "
            f"{stats['shards_failed']} failed, "
            f"{stats['shards_corrupt']} corrupt, "
            f"{stats.get('mb_per_s', 0.0):.1f} MB/s"
        )
        return state

    def _recv_checkpoint(self, quorum: Any) -> Dict[str, Any]:
        """Fetch the healing checkpoint, failing over across up-to-date
        peers when the transport supports it (pull-based HTTP). Push-based
        transports (PGTransport) stay on the single assigned source — a
        fallback peer there would never send, so failing over to it could
        only hang (see ``CheckpointTransport.supports_multi_source``)."""
        transport = self._checkpoint_transport
        # Reconstruct mode (redundancy.py): with the plane enabled, try to
        # rebuild the generation from erasure shards pulled in PARALLEL
        # from distinct peers before falling back to the serial pull. Any
        # failure — directory empty, stale generation, fewer than k shards
        # surviving — degrades to the existing heal path, so k=0 and a
        # broken plane behave identically (byte-identical path pinned by
        # tests/test_redundancy.py).
        if self._redundancy_cfg is not None and self._redundancy_cfg.enabled:
            state = self._reconstruct_checkpoint(quorum)
            if state is not None:
                return state
        if transport.supports_multi_source:
            sources = self._heal_sources(quorum)
            self._logger.info(
                f"healing required, {len(sources)} candidate source(s): "
                f"{[label for label, _ in sources]}"
            )
            try:
                return transport.recv_checkpoint_multi(
                    sources,
                    step=quorum.max_step,
                    timeout=self._timeout,
                    on_event=self._on_heal_event,
                )
            except Exception:
                # every candidate peer exhausted within the heal budget:
                # dump the ring buffer NOW, while the heal_retry/
                # heal_failover breadcrumbs are still in it. The tag
                # carries (replica, step, reason) so a same-second eject
                # dump can never overwrite this one, and the span ring
                # dumps beside it for the fleet-timeline view.
                from torchft_tpu.flight_recorder import recorder

                fr_path = recorder.dump(
                    reason="heal_exhausted",
                    quorum_id=quorum.quorum_id,
                    tag=f"{self._replica_id}_{self._group_rank}"
                    f"_s{quorum.max_step}_heal_exhausted",
                )
                self._auto_dump_trace("heal_exhausted", fr_path)
                raise
        self._logger.info(
            f"healing required, fetching metadata from "
            f"{quorum.recover_src_manager_address}"
        )
        primary_client = ManagerClient(
            quorum.recover_src_manager_address,
            connect_timeout=self._connect_timeout,
        )
        primary_client.set_retry_observer(self._on_rpc_retry)
        checkpoint_metadata = primary_client._checkpoint_metadata(
            self._group_rank, timeout=self._timeout
        )
        return transport.recv_checkpoint(
            src_rank=quorum.recover_src_replica_rank,
            metadata=checkpoint_metadata,
            step=quorum.max_step,
            timeout=self._timeout,
        )

    def _apply_pending_state_dict(self) -> None:
        assert self._healing, "must be in healing state"
        self.wait_quorum()
        pending = self._pending_state_dict
        assert pending is not None, "checkpoint was not staged"
        self._logger.info("applying pending state dict")
        with self._state_dict_lock.w_lock():
            user = pending["user"]
            for key, load_fn in self._load_state_dict_fns.items():
                if key in user:
                    load_fn(user[key])
            self._pending_state_dict = None
        self._last_quorum_healed = True
        self._bump_metric("heals")

    def _commit_pending_configure(self) -> None:
        """Apply the backend-swap half of a split reconfigure. MUST run on
        the main thread (the commit swaps live jax backend state that the
        trainer's own computations touch); called at every sync point —
        start_quorum, allreduce-after-wait, should_commit. No-op when the
        last prepare had nothing to commit."""
        with self._pending_commit_lock:
            commit, self._pending_pg_commit = self._pending_pg_commit, None
        if commit is None:
            return
        t0 = time.perf_counter()
        try:
            with trace_span("torchft::manager::configure_commit"), \
                    self._tracer.span("configure_commit", cat="quorum"):
                commit()
        except Exception as e:  # noqa: BLE001
            # force the next quorum cycle to re-run prepare+commit even if
            # the lighthouse hands back the same quorum_id: _quorum_id was
            # already recorded after prepare succeeded, so without this the
            # reconfigure would be skipped and the PG left half-configured
            self._quorum_id = -1
            self._logger.exception(f"got exception in pg configure commit: {e}")
            self.report_error(e)
        finally:
            self._record_timing("configure_commit_s", time.perf_counter() - t0)
            self._log_timing_snapshot("configure_commit")

    # -------------------------------------------------------- degrade plane
    def set_group_degree(self, full_degree: int) -> None:
        """Declare the group's intra-replica parallel degree (chips in its
        TP/PP mesh). Single-controller SPMD jobs own chips the Manager's
        ``group_world_size`` never sees, so the degrade plane scores and
        reports against this declared degree. Resets any in-progress
        degrade bookkeeping to full capacity."""
        if full_degree < 1:
            raise ValueError(f"full_degree must be >= 1, got {full_degree}")
        with self._degrade_lock:
            self._full_group_degree = full_degree
            self._group_degree = full_degree
            self._degrade_pending = None

    def set_reshard_fn(
        self, fn: Optional[Callable[[int, int], Any]]
    ) -> None:
        """Register the trainer's reshard hook, called at the commit point
        of a staged degrade as ``fn(dead_group_rank, new_degree)``. The
        hook owns the actual param movement (parallel/degrade.py reshard +
        mesh.shrink_mesh device_put); the Manager stays model-agnostic. A
        raise inside the hook falls back to the classic whole-group error
        path. May return a stats dict (e.g. DegradeStats.to_json()) that
        rides the flight-recorder breadcrumb."""
        self._reshard_fn = fn

    @property
    def group_degree(self) -> int:
        """Current intra-group parallel degree (< full while degraded)."""
        return self._group_degree

    @property
    def full_group_degree(self) -> int:
        return self._full_group_degree

    def report_member_death(self, group_rank: int) -> None:
        """Stage a degrade: chip ``group_rank`` of this group's mesh died.
        Called by the PG's abort watchdog / fault injection (via
        ``set_member_death_callback``) or directly by a trainer that
        detected the loss. Thread-safe; the shrink itself is applied at
        the next safe point (_commit_pending_degrade), making the step a
        re-planned slow step rather than a discarded one."""
        if self._degrade_cfg is None:
            return
        with self._degrade_lock:
            if self._degrade_pending is not None:
                return  # one shrink at a time; next death re-stages after
            self._degrade_pending = int(group_rank)
        self._logger.warning(
            f"group member {group_rank} died; degrade staged "
            f"(degree {self._group_degree} -> {self._group_degree - 1})"
        )

    def _commit_pending_degrade(self) -> None:
        """Apply a staged intra-group degrade at a safe point (main
        thread, same sync points as _commit_pending_configure). Shrinks
        the declared group degree, runs the registered reshard hook, and
        surfaces the event; if the surviving degree would fall below
        min_degree or the reshard fails, falls back to the classic
        whole-group error path (report_error -> this step's vote is False
        and the group leaves to heal)."""
        if self._degrade_cfg is None:
            return
        with self._degrade_lock:
            dead_rank, self._degrade_pending = self._degrade_pending, None
            degree = self._group_degree
            full = self._full_group_degree
        if dead_rank is None:
            return
        new_degree = degree - 1
        if new_degree < self._degrade_cfg.min_degree:
            self.report_error(
                RuntimeError(
                    f"group member {dead_rank} died and surviving degree "
                    f"{new_degree} is below TORCHFT_DEGRADE_MIN_DEGREE="
                    f"{self._degrade_cfg.min_degree}; falling back to "
                    "leave-heal-rejoin"
                )
            )
            return
        t0 = time.perf_counter()
        stats: Any = None
        try:
            with self._tracer.span(
                "degraded_reshard", cat="degrade", dead_rank=dead_rank
            ):
                if self._reshard_fn is not None:
                    stats = self._reshard_fn(dead_rank, new_degree)
                shrink = getattr(self._pg, "prepare_shrink", None)
                if shrink is not None:
                    commit = shrink(dead_rank)
                    if commit is not None:
                        commit()  # already at a safe point
        except Exception as e:  # noqa: BLE001
            self._logger.exception(
                f"in-place reshard after member {dead_rank} death failed; "
                "falling back to leave-heal-rejoin"
            )
            self.report_error(e)
            return
        reshard_s = time.perf_counter() - t0
        with self._degrade_lock:
            self._group_degree = new_degree
        self._record_timing("degraded_reshard_s", reshard_s)
        self._bump_counter("degrade_events")
        self._logger.warning(
            f"degraded in place: member {dead_rank} lost, group degree "
            f"{degree} -> {new_degree} (full {full}), reshard took "
            f"{reshard_s:.3f}s"
        )
        emit_event_async(
            HEALTH_EVENTS,
            replica_id=self._replica_id,
            group_rank=self._group_rank,
            step=self._step,
            quorum_id=self._quorum_id,
            kind="degrade",
            dead_group_rank=dead_rank,
            group_world_size=new_degree,
            full_group_world_size=full,
            reshard_s=reshard_s,
        )
        from torchft_tpu.flight_recorder import recorder

        recorder.record(
            "degrade",
            dead_group_rank=dead_rank,
            group_world_size=new_degree,
            full_group_world_size=full,
            reshard_s=reshard_s,
            stats=stats,
            step=self._step,
            replica=self._replica_id,
            group_rank=self._group_rank,
        )

    def restore_full_degree(self) -> None:
        """Re-promote a degraded group to full degree (a spare/repaired
        chip came back). Telemetry returns to full capacity on the next
        beat, which walks the lighthouse ledger DEGRADED -> OK."""
        if self._degrade_cfg is None:
            return
        with self._degrade_lock:
            restored = self._group_degree < self._full_group_degree
            degree = self._full_group_degree
            self._group_degree = degree
            self._degrade_pending = None
        if not restored:
            return
        self._bump_counter("restored_events")
        self._logger.warning(
            f"restored to full group degree {degree}"
        )
        emit_event_async(
            HEALTH_EVENTS,
            replica_id=self._replica_id,
            group_rank=self._group_rank,
            step=self._step,
            quorum_id=self._quorum_id,
            kind="restore",
            group_world_size=degree,
        )
        from torchft_tpu.flight_recorder import recorder

        recorder.record(
            "restore",
            group_world_size=degree,
            step=self._step,
            replica=self._replica_id,
            group_rank=self._group_rank,
        )

    # ------------------------------------------------------------ allreduce
    def allreduce(
        self,
        values: Any,
        should_quantize: bool = False,
        reduce_op: ReduceOp = ReduceOp.AVG,
    ) -> Work:
        """Fault-tolerant allreduce over a pytree of arrays.

        Returns a Work whose future resolves to the reduced pytree (with
        device placement matching the inputs). On error, the future resolves
        to a zeros pytree and the error is tracked for ``should_commit``
        (reference: manager.py:410-493).
        """
        work, _stream = self._allreduce(values, should_quantize, reduce_op)
        return work

    def allreduce_streamed(
        self,
        values: Any,
        reduce_op: ReduceOp = ReduceOp.AVG,
        bucket_cap_bytes: Optional[int] = None,
        should_quantize: bool = False,
    ) -> GradStream:
        """Streaming variant: per-bucket completion through a GradStream.

        Same numerics, error swallowing (zeros + ``should_commit`` False),
        and ordering contract as :meth:`allreduce`, but the returned handle
        exposes ``ready(i)`` per bucket so a gradient-accumulation loop can
        watch buckets land while later microbatches still compute, and
        ``wait()`` returns the reduced pytree directly.
        ``should_quantize=True`` streams the buckets COMPRESSED on a
        host-plane PG (fp8 unless ``TORCHFT_COMPRESS`` picks int8), with
        per-bucket error feedback — quantization no longer forces the
        serial monolithic path. When the tree cannot stream (single leaf,
        bucketing or streaming disabled, device-native quantized), the
        handle degenerates to one bucket covering the whole op.
        ``bucket_cap_bytes`` overrides the manager's cap for this call
        (``PureDistributedDataParallel`` routes its own cap through here).
        """
        work, stream = self._allreduce(
            values,
            should_quantize,
            reduce_op,
            bucket_cap_bytes=bucket_cap_bytes,
        )
        if stream is None:
            fut = work.get_future()
            stream = GradStream([fut], fut)
        return stream

    @traced("torchft::manager::allreduce")
    def _allreduce(
        self,
        values: Any,
        should_quantize: bool = False,
        reduce_op: ReduceOp = ReduceOp.AVG,
        bucket_cap_bytes: Optional[int] = None,
    ) -> "tuple[Work, Optional[GradStream]]":
        """Shared engine behind allreduce / allreduce_streamed.

        Returns ``(work, stream)``; ``stream`` is a GradStream when the op
        took the per-bucket streaming pipeline, else None (serial path).
        """
        import jax

        t_allreduce0 = time.perf_counter()
        self._bump_metric("allreduces")
        leaves, treedef = jax.tree_util.tree_flatten(values)

        # Bucketed path: pack a multi-leaf tree into a handful of flat
        # same-dtype buffers (shared bucketing.py; plan cached by tree
        # identity + leaf geometry) so the wire carries ceil(bytes/cap)
        # collectives instead of one per leaf. The MONOLITHIC quantized
        # path is never pre-bucketed — collectives.py already concatenates
        # into one flat wire buffer, and packing first would shift the fp8
        # rowwise-scale boundaries — but when the streaming pipeline is on
        # and the PG is host-plane, a quantized tree streams as compressed
        # buckets with error feedback instead (one codec boundary per
        # bucket, carried per-bucket residuals; see stage() below).
        cap = (
            self._bucket_cap_bytes
            if bucket_cap_bytes is None
            else int(bucket_cap_bytes)
        )
        # read before the plan gate: the gate and the compression mode both
        # depend on which plane the collective runs on (full routing
        # rationale on the comment further down)
        device_native = getattr(self._pg, "device_native", False)
        streamable_quant = (
            should_quantize and self._stream_buckets and not device_native
        )
        plan: Optional[bucketing.BucketPlan] = None
        if (
            (not should_quantize or streamable_quant)
            and len(leaves) > 1
            and cap > 0
        ):
            try:
                plan = bucketing.plan_for(leaves, cap, treedef=treedef)
            except Exception:  # noqa: BLE001 — exotic leaves fall back per-leaf
                plan = None

        # Staleness check at RESOLVE time: if the input leaf's sharding
        # references a device client that is no longer the live backend
        # (ProcessGroupXLA tore down + rejoined its per-quorum
        # jax.distributed world between the caller computing `values`
        # and this resolve), a device_put onto it can SUCCEED and
        # produce an array the next jitted computation rejects as
        # "incompatible devices". Land such leaves on the live backend
        # instead — _sync_device_world re-lands the user's own state
        # the same way at should_commit. LAZY on purpose: jax.devices()
        # initializes the backend, and a pure-host tree must never
        # trigger that (a wedged accelerator plugin hangs init — the
        # host plane has to keep working through exactly that state).
        live_client = [False]

        def _is_live(sharding) -> bool:
            if live_client[0] is False:
                try:
                    live_client[0] = getattr(
                        jax.devices()[0], "client", None
                    )
                except Exception:  # noqa: BLE001
                    live_client[0] = None
            if live_client[0] is None:
                return True
            try:
                dev = next(iter(sharding.device_set))
                return getattr(dev, "client", None) is live_client[0]
            except Exception:  # noqa: BLE001
                return False

        def place_leaf(orig: Any, host: Any) -> Any:
            # restore one reduced slice to its original leaf's placement —
            # shared by the monolithic rebuild and the per-bucket pipeline
            # so both paths land leaves through identical expressions
            import jax.numpy as jnp

            if isinstance(orig, jax.Array):
                if _is_live(orig.sharding):
                    return jax.device_put(host, orig.sharding)
                return jnp.asarray(np.asarray(host))
            return np.asarray(host)

        def rebuild(host_leaves: List[np.ndarray]) -> Any:
            out = [
                place_leaf(orig, host)
                for orig, host in zip(leaves, host_leaves)
            ]
            return jax.tree_util.tree_unflatten(treedef, out)

        def zeros() -> Any:
            return rebuild([np.zeros(np.shape(l), _np_dtype(l)) for l in leaves])

        if self.errored():
            return DummyWork(zeros()), None

        self.wait_quorum()
        # a reconfigure that landed during the forward pass commits its
        # backend swap here, before the collective touches the PG — this
        # is the "next safe point" for steps that skip should_commit
        self._commit_pending_configure()
        if self.errored():
            return DummyWork(zeros()), None
        num_participants = self.num_participants()

        # Device-native PGs (ProcessGroupXLA) take jax.Arrays straight
        # through — the collective runs on device over ICI/DCN with no
        # host staging (VERDICT weak #4: the D2H round-trip on the caller
        # thread). The quantized path likewise keeps everything on device:
        # the Pallas kernels quantize there and the compressed payload
        # ships as packed uint8 device arrays through the PG's own
        # collectives (collectives.py _pack_wire_device), so on hardware
        # the fp8 exchange rides ICI with zero host staging. Host-plane
        # PGs with plain numpy inputs get the numpy staging they require.
        # Only a device-native PG (ProcessGroupXLA) bypasses the staging
        # worker: its ops rendezvous by (kind, seq) so issue order across
        # threads cannot mismatch. On a host PG EVERYTHING — including the
        # quantized pipeline, whose alltoall/allgather would otherwise be
        # issued from an unordered helper thread — goes through the one
        # ordered staging worker (host exchange matches messages purely by
        # arrival order; cross-replica issue order is the contract).
        # (device_native itself is read above, before the plan gate.)

        pg_reduce_op = reduce_op
        if reduce_op == ReduceOp.AVG:
            if not all(np.issubdtype(_np_dtype(l), np.floating) or
                       "bfloat16" in str(_np_dtype(l)) for l in leaves):
                raise ValueError("AVG allreduce requires floating point arrays")
            pg_reduce_op = ReduceOp.SUM

        def normalize(f: Future) -> Any:
            reduced = f.value()
            if reduce_op == ReduceOp.AVG and num_participants > 0:
                reduced = [
                    (r / num_participants).astype(_np_dtype(r)) for r in reduced
                ]
            if plan is not None:
                # slice the reduced flats back into per-leaf arrays; rebuild
                # then restores each ORIGINAL leaf's device placement
                reduced = bucketing.unpack(reduced, plan)
            return rebuild(reduced)

        def _time_allreduce(_f: Future) -> None:
            # submission → resolve wall clock of the most recent
            # collective, for the steady-state budget split
            # (ft_overhead harness; see timings())
            self._record_timing(
                "allreduce_s", time.perf_counter() - t_allreduce0
            )

        try:
            if plan is not None and self._stream_buckets:
                # ---------------- streaming bucket pipeline ----------------
                # One PG collective PER BUCKET instead of one for the whole
                # plan, three stages per bucket: pack (D2H / device concat),
                # wire (the PG's dispatch thread or XLA), unpack (divide +
                # slice + land on device, on the dedicated unpack worker).
                # Bucket i+1 packs while bucket i rides the wire and bucket
                # i−1 unpacks — no stage ever waits for the LAST bucket's
                # wire, which is exactly what the monolithic path did.
                # Numerics are bit-identical to the serial path: per-bucket
                # collectives reduce each flat independently just like one
                # call carrying the list, and divide/slice/land use the same
                # expressions (normalize / unpack / place_leaf).
                import jax.numpy as jnp

                n_buckets = len(plan)
                # per-bucket (start, end) wall-clock marks per stage, for
                # pack_s/wire_s/unpack_s + overlap_efficiency in timings()
                marks: List[Dict[str, Any]] = [{} for _ in range(n_buckets)]
                bucket_futs: List[Future] = [Future() for _ in range(n_buckets)]
                # aggregate: every bucket landed -> reassembled pytree.
                # final_fut is fed from the join but owned here so the
                # staging watchdog / shutdown sweep can fail it directly.
                final_fut: Future = Future()

                def _assemble(f: Future) -> Any:
                    placed: Dict[int, Any] = {}
                    for pairs in f.value():
                        for idx, v in pairs:
                            placed[idx] = v
                    return jax.tree_util.tree_unflatten(
                        treedef, [placed[i] for i in range(len(leaves))]
                    )

                def _feed_final(f: Future) -> None:
                    try:
                        v = f.value()
                    except Exception as e:  # noqa: BLE001
                        try:
                            final_fut.set_exception(e)
                        except RuntimeError:
                            pass
                        return
                    try:
                        final_fut.set_result(v)
                    except RuntimeError:
                        pass

                join_futures(bucket_futs).then(_assemble).add_done_callback(
                    _feed_final
                )

                participating = self.is_participating()
                pool = self._buffer_pool

                def _land_bucket(i: int, flat: Any, pooled_buf: Any) -> None:
                    # stage 3, off the PG dispatch thread: AVG divide +
                    # slice + device landing for ONE bucket. A failure here
                    # fails the aggregate via the join; earlier buckets'
                    # landed slices are only reachable through the aggregate
                    # tree, so a mid-stream error can never leak a
                    # partially-applied reduction.
                    try:
                        t0u = time.perf_counter()
                        if is_compressed_wire(flat):
                            # the bucket rode the wire compressed; the codes
                            # carry the reduced SUM, restored here at the
                            # plan's bucket dtype so divide/slice/land below
                            # run the exact uncompressed expressions
                            flat = decompress_bucket(flat)
                        if reduce_op == ReduceOp.AVG and num_participants > 0:
                            flat = (flat / num_participants).astype(
                                _np_dtype(flat)
                            )
                        pairs = [
                            (idx, place_leaf(leaves[idx], val))
                            for idx, val in bucketing.unpack_bucket(
                                flat, plan, i
                            )
                        ]
                        marks[i]["unpack"] = (t0u, time.perf_counter())
                        if pooled_buf is not None and not any(
                            isinstance(v, np.ndarray)
                            and np.shares_memory(v, pooled_buf)
                            for _idx, v in pairs
                        ):
                            # recycle this bucket's staging buffer the
                            # moment it lands (success only; never when the
                            # PG passed it through as its own result)
                            pool.release(pooled_buf)
                        bucket_futs[i].set_result(pairs)
                    except Exception as e:  # noqa: BLE001
                        try:
                            bucket_futs[i].set_exception(e)
                        except RuntimeError:
                            pass

                if device_native:
                    # device plane: issue per-bucket collectives straight
                    # from the caller thread — ProcessGroupXLA rendezvouses
                    # ops by (kind, seq), and per-bucket ops let XLA overlap
                    # ICI transfers with adjacent compute
                    t0p = time.perf_counter()
                    if participating:
                        up = [
                            l if isinstance(l, jax.Array) else jnp.asarray(l)
                            for l in leaves
                        ]
                        dev_flats, _ = bucketing.pack(up, plan)
                    else:
                        dev_flats = [
                            jnp.zeros(size, dtype)
                            for size, dtype in zip(plan.sizes, plan.dtypes)
                        ]
                    marks[0]["pack"] = (t0p, time.perf_counter())
                    for i in range(n_buckets):
                        t0w = time.perf_counter()
                        w = self._pg.allreduce([dev_flats[i]], pg_reduce_op)

                        def _wire_done(
                            f: Future, i: int = i, t0w: float = t0w
                        ) -> None:
                            marks[i]["wire"] = (t0w, time.perf_counter())
                            try:
                                flat = f.value()[0]
                            except Exception as e:  # noqa: BLE001
                                try:
                                    bucket_futs[i].set_exception(e)
                                except RuntimeError:
                                    pass
                                return
                            _land_bucket(i, flat, None)

                        w.get_future().add_done_callback(_wire_done)
                else:
                    # host plane: capture on the caller thread (donation
                    # safety, same as the serial path), then ONE staging
                    # task walks the buckets — D2H bucket i, non-blocking
                    # dispatch, straight on to bucket i+1 while the PG's
                    # dispatch thread runs the wire. A single task keeps
                    # per-plan dispatch atomic across concurrent callers,
                    # preserving cross-replica arrival order (the SPMD
                    # contract of the host exchange).
                    if participating:
                        capture, pooled = bucketing.pack(
                            leaves, plan, pool=pool
                        )
                    else:
                        capture, pooled = None, []
                    pooled_ids = {id(b) for b in pooled}
                    stage_timeout = self._timeout

                    # wire compression: TORCHFT_COMPRESS / compress= knob,
                    # plus should_quantize callers who land here (streaming
                    # on, host plane) defaulting to fp8. Non-float buckets
                    # ride uncompressed — the decision depends only on the
                    # shared plan + mode, so it is SPMD-consistent across
                    # replicas. Non-participants compress their zero
                    # contribution too (the ring needs uniform wire
                    # geometry) but never touch the EF residuals.
                    compress_mode = self._compress
                    if should_quantize and compress_mode == "off":
                        compress_mode = "fp8"
                    if compress_mode != "off":
                        bucket_modes = [
                            compress_mode
                            if _is_float_dtype(plan.dtypes[i])
                            else "off"
                            for i in range(n_buckets)
                        ]
                        ef_store = (
                            self._bucket_residuals(plan)
                            if participating
                            else None
                        )
                    else:
                        bucket_modes = ["off"] * n_buckets
                        ef_store = None

                    def _stage_deadline() -> None:
                        try:
                            final_fut.set_exception(
                                TimeoutError("allreduce staging timed out")
                            )
                        except RuntimeError:
                            pass

                    def stage() -> None:
                        try:
                            from torchft_tpu.futures import arm_deadline

                            cancel = arm_deadline(
                                _stage_deadline, stage_timeout
                            )
                            final_fut.add_done_callback(lambda _f: cancel())
                            for i in range(n_buckets):
                                t0b = time.perf_counter()
                                if capture is None:
                                    host_flat = np.zeros(
                                        (plan.sizes[i],), plan.dtypes[i]
                                    )
                                    pooled_buf = None
                                else:
                                    host_flat = np.asarray(capture[i])
                                    pooled_buf = (
                                        capture[i]
                                        if id(capture[i]) in pooled_ids
                                        else None
                                    )
                                payload: Any = host_flat
                                if bucket_modes[i] != "off":
                                    # quantize inside the pack stage so
                                    # pack_s absorbs the codec cost and
                                    # overlap accounting stays honest
                                    payload = self._compress_bucket_ef(
                                        host_flat,
                                        bucket_modes[i],
                                        plan.dtypes[i],
                                        ef_store,
                                        i,
                                    )
                                w = self._pg.allreduce(
                                    [payload], pg_reduce_op
                                )
                                t1b = time.perf_counter()
                                marks[i]["pack"] = (t0b, t1b)

                                def _wire_done(
                                    f: Future,
                                    i: int = i,
                                    t0w: float = t1b,
                                    pooled_buf: Any = pooled_buf,
                                ) -> None:
                                    # runs on the PG dispatch thread — keep
                                    # it tiny: record, then hand unpack to
                                    # the unpack worker so the NEXT bucket's
                                    # wire starts immediately
                                    marks[i]["wire"] = (
                                        t0w,
                                        time.perf_counter(),
                                    )
                                    try:
                                        flat = f.value()[0]
                                    except Exception as e:  # noqa: BLE001
                                        try:
                                            bucket_futs[i].set_exception(e)
                                        except RuntimeError:
                                            pass
                                        return
                                    try:
                                        self._unpack_executor.submit(
                                            _land_bucket, i, flat, pooled_buf
                                        )
                                    except RuntimeError as e:  # shutdown
                                        try:
                                            bucket_futs[i].set_exception(e)
                                        except RuntimeError:
                                            pass

                                w.get_future().add_done_callback(_wire_done)
                        except Exception as e:  # noqa: BLE001
                            for bf in bucket_futs:
                                try:
                                    bf.set_exception(e)
                                except RuntimeError:
                                    pass

                    from torchft_tpu.futures import arm_deadline as _arm

                    # submit + register atomically vs the shutdown sweep,
                    # with the same depth-aware submission backstop as the
                    # serial path (a wedged op ahead of us means stage()
                    # never runs and never arms the tight deadline)
                    with self._staged_lock:
                        if self._staging_down:
                            raise RuntimeError("manager is shut down")
                        depth = len(self._staged_pending)
                        backstop_cancel = _arm(
                            _stage_deadline, (depth + 2) * stage_timeout
                        )
                        final_fut.add_done_callback(
                            lambda _f: backstop_cancel()
                        )
                        exec_fut = self._staging_executor.submit(stage)
                        pair = (exec_fut, final_fut)
                        self._staged_pending.append(pair)

                    def _unpin(_f: Future) -> None:
                        with self._staged_lock:
                            try:
                                self._staged_pending.remove(pair)
                            except ValueError:
                                pass

                    final_fut.add_done_callback(_unpin)

                wrapped = self.wrap_future(
                    final_fut, zeros, arm_timeout=device_native
                )
                wrapped.add_done_callback(_time_allreduce)

                def _finalize_pipeline(_f: Future) -> None:
                    try:
                        self._record_pipeline_timings(marks)
                    except Exception:  # noqa: BLE001
                        self._logger.exception(
                            "failed to record pipeline timings"
                        )

                wrapped.add_done_callback(_finalize_pipeline)
                stream = GradStream(bucket_futs, wrapped)
                return FutureWork(wrapped), stream

            if device_native:
                import jax.numpy as jnp

                if plan is not None:
                    if not self.is_participating():
                        # zero contribution, built directly at bucket shape
                        # (cheaper than zeroing per leaf then packing)
                        dev_leaves = [
                            jnp.zeros(size, dtype)
                            for size, dtype in zip(plan.sizes, plan.dtypes)
                        ]
                    else:
                        up = [
                            l if isinstance(l, jax.Array) else jnp.asarray(l)
                            for l in leaves
                        ]
                        dev_leaves, _ = bucketing.pack(up, plan)
                else:
                    dev_leaves = [
                        l if isinstance(l, jax.Array) else jnp.asarray(l)
                        for l in leaves
                    ]
                    if not self.is_participating():
                        dev_leaves = [jnp.zeros_like(h) for h in dev_leaves]
                if should_quantize:
                    from torchft_tpu.collectives import allreduce_quantized

                    work = allreduce_quantized(dev_leaves, pg_reduce_op, self._pg)
                else:
                    work = self._pg.allreduce(dev_leaves, pg_reduce_op)
                fut = work.get_future()
            else:
                # Host plane: the D2H of a full gradient pytree would block
                # the train loop if staged on the caller thread (round-2
                # verdict weak #4). Stage + dispatch on the ordered staging
                # thread instead — one worker, so collectives still issue
                # in caller order on every replica (the SPMD contract).
                staged_fut: Future = Future()
                fut = staged_fut
                participating = self.is_participating()

                # Capture on the caller thread: the staging thread reads
                # these AFTER allreduce() returns, by which time the
                # caller's next jitted step may have donated (deleted) the
                # device buffers or overwritten a reused numpy buffer.
                # jax.Arrays get a device-side copy (HBM bandwidth, async
                # dispatch — far cheaper than blocking the train loop on
                # the D2H transfer); numpy leaves get a host memcpy.
                # Non-participants skip the capture entirely — they
                # contribute zeros built from shapes alone (the reference
                # zeroes the buffer in place; arrays are immutable here).
                import jax.numpy as jnp

                if participating:
                    if plan is not None:
                        # the packed flats ARE the capture: device groups
                        # concatenate into a fresh (donation-safe) buffer,
                        # host groups copy into a pool-recycled one — no
                        # second per-leaf copy
                        capture, pooled = bucketing.pack(
                            leaves, plan, pool=self._buffer_pool
                        )
                    else:
                        capture = [
                            jnp.copy(l) if isinstance(l, jax.Array)
                            else np.array(l, copy=True)
                            for l in leaves
                        ]
                        pooled = []
                else:
                    capture = None
                    pooled = []
                if plan is not None:
                    zero_specs = [
                        ((size,), dtype)
                        for size, dtype in zip(plan.sizes, plan.dtypes)
                    ]
                else:
                    zero_specs = [(np.shape(l), _np_dtype(l)) for l in leaves]
                stage_timeout = self._timeout

                def _stage_deadline() -> None:
                    # fail-the-future watchdog armed when staging BEGINS
                    # (not at submission: queue time behind an in-flight
                    # quantized sync must not count against this op)
                    try:
                        staged_fut.set_exception(
                            TimeoutError("allreduce staging timed out")
                        )
                    except RuntimeError:
                        pass

                def stage() -> None:
                    """D2H + dispatch only — the PG's own ordered worker
                    runs the wire, and the result chains in via callback.
                    Blocking here would serialize overlapped allreduces on
                    this one thread and charge queue time against later
                    calls' wrap_future timeouts. EXCEPTION: the quantized
                    pipeline runs to completion here — its alltoall and
                    allgather must be issued in staged order (they would
                    otherwise race other staged ops from its helper
                    thread), and quantized syncs are rare boundary events
                    (DiLoCo) where the serialization is acceptable."""
                    try:
                        from torchft_tpu.futures import arm_deadline

                        # The tight deadline spans the WHOLE staged op —
                        # D2H, dispatch, AND the wire phase the PG worker
                        # resolves via callback after this function
                        # returns. A `with` around just this frame would
                        # disarm at dispatch, leaving a never-resolving
                        # wire (hung peer whose abort path also fails)
                        # unbounded. Cancelled the moment staged_fut
                        # settles, so queue time behind an in-flight
                        # quantized sync still never counts against it.
                        cancel = arm_deadline(_stage_deadline, stage_timeout)
                        staged_fut.add_done_callback(lambda _f: cancel())
                        if should_quantize:
                            from torchft_tpu.collectives import allreduce_quantized

                            if capture is None:
                                wire_leaves = [
                                    np.zeros(s, d) for s, d in zero_specs
                                ]
                            else:
                                # keep jax copies as-is: single-device
                                # trees take the Pallas engine
                                wire_leaves = capture
                            w = allreduce_quantized(
                                wire_leaves, pg_reduce_op, self._pg
                            )
                            staged_fut.set_result(
                                w.get_future().wait(stage_timeout)
                            )
                            return
                        if capture is None:
                            host_leaves = [
                                np.zeros(s, d) for s, d in zero_specs
                            ]
                        else:
                            host_leaves = [np.asarray(l) for l in capture]
                        w = self._pg.allreduce(host_leaves, pg_reduce_op)

                        def _xfer(f: Future) -> None:
                            try:
                                exc = f.exception()
                                if exc is not None:
                                    staged_fut.set_exception(exc)
                                else:
                                    staged_fut.set_result(f.value())
                            except RuntimeError:
                                pass

                        w.get_future().add_done_callback(_xfer)
                    except Exception as e:  # noqa: BLE001
                        try:
                            staged_fut.set_exception(e)
                        except RuntimeError:
                            pass

                # submit + register atomically vs the shutdown sweep: a pair
                # appended after the sweep would never have its staged
                # future failed (full-timeout stall), and a submit after
                # executor shutdown raises anyway
                from torchft_tpu.futures import arm_deadline as _arm

                with self._staged_lock:
                    if self._staging_down:
                        raise RuntimeError("manager is shut down")
                    # Submission-time depth-aware BACKSTOP: if an op ahead
                    # of us wedges its stage() forever (D2H against a hung
                    # device, a dispatch that never returns), our stage()
                    # never runs and the tight stage-start deadline is
                    # never armed. Healthy queue time is bounded by one
                    # deadline per op ahead (each stage() blocks at most
                    # stage_timeout), so depth+2 slots never fire on a
                    # healthy queue; both timers race to the same
                    # set_exception and the loser is a no-op.
                    depth = len(self._staged_pending)
                    backstop_cancel = _arm(
                        _stage_deadline, (depth + 2) * stage_timeout
                    )
                    staged_fut.add_done_callback(lambda _f: backstop_cancel())
                    exec_fut = self._staging_executor.submit(stage)
                    pair = (exec_fut, staged_fut)
                    self._staged_pending.append(pair)

                def _unpin(_f: Future) -> None:
                    # release the (gradient-sized) result reference as soon
                    # as the wire resolves, not at the next allreduce
                    with self._staged_lock:
                        try:
                            self._staged_pending.remove(pair)
                        except ValueError:
                            pass

                staged_fut.add_done_callback(_unpin)

                if pooled:
                    pool = self._buffer_pool

                    def _recycle(f: Future) -> None:
                        # Recycle pooled staging buffers once the wire is
                        # done — but only on success (an errored/timed-out
                        # op's wire thread may still read its buffer), and
                        # never a buffer the PG passed through as its own
                        # result (world-1 short circuits): the caller's
                        # rebuilt tree may hold views into it.
                        try:
                            if f.exception() is not None:
                                return
                            out = f.value()
                        except Exception:  # noqa: BLE001
                            return
                        for b in pooled:
                            if any(
                                isinstance(o, np.ndarray)
                                and np.shares_memory(o, b)
                                for o in out
                            ):
                                continue
                            pool.release(b)

                    staged_fut.add_done_callback(_recycle)

            fut = fut.then(normalize)
            # device path: submission-time timer (op starts immediately).
            # host path: the stage-start watchdog above owns the deadline —
            # a submission timer would charge queue time behind an
            # in-flight quantized sync against this op.
            fut = self.wrap_future(fut, zeros, arm_timeout=device_native)
            fut.add_done_callback(_time_allreduce)
            return FutureWork(fut), None
        except Exception as e:  # noqa: BLE001
            self._logger.exception(f"got exception in allreduce -- skipping remaining: {e}")
            self.report_error(e)
            return DummyWork(zeros()), None

    # ------------------------------------------------------------ metrics
    def _bump_metric(self, name: str) -> None:
        with self._metrics_lock:
            self._metrics[name] += 1

    def metrics(self) -> Dict[str, int]:
        """Lifetime counters for operators/tests: quorums completed,
        PG reconfigures, live heals applied, commits, commit failures
        (monotonic total, unlike the protocol's consecutive
        ``_commit_failures``), allreduce calls, and errors reported. The
        structured event streams (observability.py) log the same moments
        as events; this is the cheap queryable aggregate."""
        with self._metrics_lock:
            return dict(self._metrics)

    # ------------------------------------------------------------ tracing
    @property
    def tracer(self) -> SpanRecorder:
        """This replica's span recorder (see :mod:`torchft_tpu.tracing`)."""
        return self._tracer

    def dump_trace(self, path: "str | Path | None" = None) -> Optional[Path]:
        """Write the span ring as a merge-ready JSON dump and return its
        path (None when no destination is configured — set
        ``TORCHFT_TRACE_DIR`` or pass a path). Feed one dump per replica
        to ``python -m torchft_tpu.trace merge`` for the fleet timeline."""
        return self._tracer.dump(path)

    def _auto_dump_trace(self, reason: str, fr_path: Optional[Path]) -> None:
        """Drop the span ring next to a flight-recorder dump so the two
        postmortem artifacts travel together (same directory, matching
        reason suffix); falls back to the default trace destination when
        the FR dump itself was disabled. Never raises."""
        try:
            path = None
            if fr_path is not None:
                path = Path(fr_path).parent / (
                    f"trace_{self._replica_id}_{self._group_rank}"
                    f"_s{self._step}_{reason}.json"
                )
            out = self._tracer.dump(path)
            if out is not None:
                self._logger.warning(f"span ring dumped to {out} ({reason})")
        except Exception:  # noqa: BLE001 — postmortem path must not raise
            self._logger.exception("trace auto-dump failed")

    @property
    def metrics_port(self) -> Optional[int]:
        """Bound TCP port of the Prometheus ``/metrics`` endpoint (None
        when not serving; enable via ``metrics_port=`` or
        ``TORCHFT_METRICS_PORT``)."""
        return (
            self._metrics_server.port
            if self._metrics_server is not None
            else None
        )

    def _refresh_metrics(self) -> None:
        """Scrape-time sync of gauges/counters into the Prometheus
        registry (the MetricsServer calls this before each render).
        Histograms fill at :meth:`_record_timing` write time; everything
        here is a last-value gauge or an absolute cumulative counter, so
        re-rendering per scrape can't double-book."""
        reg = self._metrics_registry
        if reg is None:
            return
        for name, value in self.timings().items():
            if not isinstance(value, (int, float)):
                continue
            if name in _COUNTER_TIMINGS:
                reg.counter_set(
                    f"torchft_manager_{name}_total",
                    float(value),
                    f"Cumulative {name} (Manager.timings()).",
                )
            else:
                reg.gauge_set(
                    f"torchft_manager_{name}",
                    float(value),
                    f"Last-value {name} (Manager.timings()).",
                )
        for name, value in self.metrics().items():
            reg.counter_set(
                f"torchft_manager_{name}_total",
                float(value),
                f"Lifetime {name} (Manager.metrics()).",
            )
        reg.gauge_set(
            "torchft_manager_step", float(self._step), "Current manager step."
        )
        reg.gauge_set(
            "torchft_manager_quorum_id",
            float(self._quorum_id),
            "Quorum id of the current process-group generation.",
        )
        tstats = self._tracer.stats()
        reg.counter_set(
            "torchft_manager_trace_spans_total",
            tstats["recorded"],
            "Spans recorded into the trace ring since construction.",
        )
        try:
            wire_fn = getattr(self._pg, "wire_stats", None)
            wire = wire_fn() if wire_fn is not None else {}
        except Exception:  # noqa: BLE001
            wire = {}
        for name, value in (wire or {}).items():
            if not isinstance(value, (int, float)):
                continue
            if name.startswith("bytes_"):
                reg.counter_set(
                    f"torchft_manager_wire_{name}_total",
                    float(value),
                    f"Cumulative transport {name} across PG generations.",
                )
            else:
                reg.gauge_set(
                    f"torchft_manager_wire_{name}",
                    float(value),
                    f"Transport {name} (ProcessGroup.wire_stats()).",
                )
        if self._manager is not None:
            try:
                skew_fn = getattr(self._manager, "clock_skew", None)
                skew = skew_fn() if skew_fn is not None else {}
            except Exception:  # noqa: BLE001
                skew = {}
            if skew:
                reg.gauge_set(
                    "torchft_manager_clock_skew_ms",
                    float(skew.get("skew_ms", 0.0)),
                    "Estimated clock skew vs the lighthouse "
                    "(best = minimum-RTT heartbeat sample).",
                )
                reg.gauge_set(
                    "torchft_manager_clock_skew_rtt_ms",
                    float(skew.get("rtt_ms", 0.0)),
                    "Heartbeat RTT of the best skew sample.",
                )

    def _record_timing(self, name: str, value: float) -> None:
        with self._metrics_lock:
            self._timings[name] = value
        # histograms accumulate at write time (the scrape-time refresh only
        # syncs last-value gauges and cumulative counters — re-observing a
        # last-value snapshot per scrape would double-book the same phase)
        if self._metrics_registry is not None and name.endswith("_s"):
            self._metrics_registry.observe(
                f"torchft_manager_{name[:-2]}_seconds",
                value,
                f"Manager {name[:-2]} phase wall-clock (seconds).",
            )

    def _bump_counter(self, name: str, n: float = 1.0) -> None:
        """Increment a cumulative resilience counter in timings()."""
        with self._metrics_lock:
            self._timings[name] = self._timings.get(name, 0.0) + n

    def _on_rpc_retry(self, method: str, attempt: int, exc: BaseException) -> None:
        """Retry observer installed on both manager RPC clients: a
        control-plane blip shorter than the quorum timeout degrades to a
        slower step, and this is the audit trail that says so."""
        self._bump_counter("rpc_retries")
        self._tracer.instant(
            "rpc_retry", cat="rpc", method=method, attempt=attempt
        )
        self._logger.warning(
            f"RPC {method} retrying (attempt {attempt}) after {exc!r}"
        )
        from torchft_tpu.flight_recorder import recorder

        recorder.record(
            "rpc_retry",
            method=method,
            attempt=attempt,
            error=repr(exc),
            step=self._step,
            replica=self._replica_id,
            group_rank=self._group_rank,
        )

    def _on_collective_reroute(self, pair, attempt: int) -> None:
        """Re-route observer installed on PGs that support the compressed
        ring: a mid-collective link failure degraded to a re-routed slow
        step instead of a swallowed one, and this is the audit trail."""
        self._bump_counter("collective_reroute")
        self._tracer.instant(
            "reroute", cat="rpc", link=list(pair), attempt=attempt
        )
        self._logger.warning(
            f"collective re-routed around dead link {pair} "
            f"(attempt {attempt})"
        )
        from torchft_tpu.flight_recorder import recorder

        recorder.record(
            "collective_reroute",
            link=tuple(pair),
            attempt=attempt,
            step=self._step,
            replica=self._replica_id,
            group_rank=self._group_rank,
        )

    def _bucket_residuals(self, plan: "bucketing.BucketPlan") -> List[Any]:
        """Per-bucket error-feedback residual slots for one plan.

        Keyed by plan identity (plans are cached and reused every step, so
        the same tree keeps the same slots); weakref-keyed so an evicted
        plan drops its residual buffers with it. Slots start None and are
        allocated from the BufferPool on first compression."""
        with self._ef_lock:
            store = self._ef_residuals.get(plan)
            if store is None:
                store = [None] * len(plan)
                self._ef_residuals[plan] = store
            return store

    def _compress_bucket_ef(
        self,
        host_flat: np.ndarray,
        mode: str,
        out_dtype: Any,
        store: Optional[List[Any]],
        i: int,
    ) -> Any:
        """Quantize one packed bucket for the wire, with error feedback.

        The residual — everything rowwise quantization rounded away this
        step — is carried into the NEXT step's bucket before quantizing,
        so the compression error stays bounded (standard EF-SGD) instead
        of accumulating across LocalSGD/DiLoCo syncs. ``store`` is None
        for non-participants (zero contribution, nothing to feed back).
        Runs on the single staging worker, so residual updates for one
        plan never race."""
        resid = store[i] if store is not None else None
        if resid is not None:
            # one fused pass: the add IS the private f32 copy
            work = host_flat + resid
        else:
            work = np.asarray(host_flat, dtype=np.float32)
        wire = compress_bucket(work, mode, dtype=out_dtype)
        if store is not None:
            resid = store[i]
            if resid is None:
                resid = self._buffer_pool.acquire(work.size, np.float32)
                store[i] = resid
            np.subtract(
                work, decompress_bucket(wire, np.float32), out=resid
            )
        return wire

    def _record_pipeline_timings(self, marks: List[Dict[str, Any]]) -> None:
        """Fold one streamed allreduce's per-bucket stage marks into
        timings(): summed ``allreduce_pack_s`` / ``allreduce_wire_s`` /
        ``allreduce_unpack_s``, the bucket count, and
        ``overlap_efficiency`` — the fraction of total wire time that ran
        concurrently with OTHER buckets' pipeline stages (a lower bound on
        the real win: overlap with the caller's own compute, e.g. the next
        microbatch's grad_fn, is invisible from here). Emitted to the
        ``torchft_timings`` stream through the bounded async drain."""
        stats = _pipeline_overlap_stats(marks)
        with self._metrics_lock:
            self._timings.update(stats)
        for i, mark in enumerate(marks):
            for stage in ("pack", "wire", "unpack"):
                span = mark.get(stage)
                if span is None:
                    continue
                t0_pc, t1_pc = span
                self._tracer.record_rel(
                    stage, cat="allreduce", t0_pc=t0_pc, t1_pc=t1_pc, bucket=i
                )
        self._log_timing_snapshot(ALLREDUCE_PIPELINE_PHASE)

    def timings(self) -> Dict[str, float]:
        """Per-phase wall-clock of the most recent quorum cycle:
        ``quorum_overlap_s`` (control-plane time on the quorum thread —
        hidden from the train step under async quorum),
        ``configure_prepare_s`` / ``configure_commit_s`` (the split
        reconfigure; commit is the only part that serializes with the
        trainer), and ``heal_send_s`` / ``heal_recv_s`` plus
        ``heal_chunks`` / ``heal_mb_per_s`` when the checkpoint transport
        reports chunk-stream stats. Streamed allreduces add
        ``allreduce_pack_s`` / ``allreduce_wire_s`` / ``allreduce_unpack_s``
        / ``allreduce_buckets`` / ``overlap_efficiency`` (see
        :meth:`_record_pipeline_timings`). Keys appear once the phase has
        run.

        Also carries the CUMULATIVE resilience counters (present from
        construction, never reset): ``heal_attempts`` (initial heal tries
        plus same-source retries), ``heal_failovers`` (mid-heal switches to
        a fallback peer), ``rpc_retries`` (retried control-plane calls),
        and ``chunk_crc_failures`` (chunks refetched after an integrity
        mismatch).

        When healthwatch telemetry is enabled (group leader talking to a
        lighthouse with ``TORCHFT_HEALTH_MODE`` != ``off``) it also
        mirrors the lighthouse's latest health summary for THIS replica:
        ``health_state`` (0=ok 1=warn 2=ejected 3=probation),
        ``straggler_score`` (quorum-relative modified z-score), and the
        cumulative ``ejections`` / ``readmissions`` counts. All four are
        seeded to 0.0 at construction.

        ``dropped_events`` / ``trace_dropped`` count observability losses:
        telemetry events shed by the bounded async drain under
        saturation, and spans overwritten in the trace ring. Both planes
        are deliberately lossy (they must never stall the step), so these
        are the honesty counters — nonzero means the record is
        incomplete, warned once per Manager."""
        with self._metrics_lock:
            out = dict(self._timings)
        # Two-level control plane: when this replica is configured for a
        # lighthouse aggregator (TORCHFT_LIGHTHOUSE_AGGREGATOR), mirror
        # which upstream the control RPCs use (``via_aggregator``) and the
        # cumulative aggregator->root ``aggregator_failovers``.
        cs_fn = getattr(getattr(self, "_manager", None), "control_status", None)
        if cs_fn is not None:
            try:
                cs = cs_fn() or {}
                if cs.get("aggregator_addr"):
                    out["via_aggregator"] = 1.0 if cs.get("via_aggregator") else 0.0
                    out["aggregator_failovers"] = float(cs.get("failovers", 0))
            except Exception:  # noqa: BLE001 — advisory plane
                pass
        out["dropped_events"] = float(get_event_drain().dropped)
        out["trace_dropped"] = self._tracer.stats()["dropped"]
        if (
            out["dropped_events"] + out["trace_dropped"] > 0
            and not self._dropped_events_warned
        ):
            self._dropped_events_warned = True
            self._logger.warning(
                f"observability queues saturated: "
                f"{int(out['dropped_events'])} telemetry event(s) and "
                f"{int(out['trace_dropped'])} span(s) dropped so far — "
                f"timings/trace records are incomplete (raise "
                f"{TRACE_BUFFER_ENV} or reduce scrape/step rate)"
            )
        return out

    # ------------------------------------------------------ serving plane
    def attach_serve_publisher(
        self,
        publisher: Any,
        params_fn: Optional[Callable[[], Any]] = None,
    ) -> None:
        """Attach a serving-plane SnapshotPublisher: every committed step
        is published as a versioned snapshot stamped ``(quorum_id, step)``
        (docs/serving.md).  ``params_fn`` selects what to publish (default:
        the registered user state dict).  Group leader only — follower
        ranks ignore the attach so a replica announces exactly once.
        Publishing is advisory: failures log, they never fail a commit."""
        if self._group_rank != 0:
            return
        self._serve_publisher = publisher
        self._serve_params_fn = (
            params_fn if params_fn is not None else self.user_state_dict
        )

    def _serve_publish_committed(self) -> None:
        """Commit-path hook: hand the just-committed params to the
        publisher.  The host copy happens here (so the next step cannot
        tear the snapshot); encoding and announcing ride the publisher's
        own thread.  Never raises — the serving plane is advisory."""
        t0 = time.perf_counter()
        try:
            self._serve_publisher.publish_async(
                self._quorum_id, self._step, self._serve_params_fn()
            )
            self._bump_counter("serve_published_total")
        except Exception:  # noqa: BLE001 — advisory plane
            self._bump_counter("serve_publish_errors_total")
            self._logger.exception("serve snapshot publish failed")
        self._record_timing("serve_publish_s", time.perf_counter() - t0)

    def _stage_redundancy_committed(self) -> None:
        """Round-start hook for the redundancy plane: hand the committed
        composite state (the update the caller just applied, labeled with
        the step about to run — exactly what a healer joining this round
        must load) to the ShardStager. The hot path pays one host
        snapshot copy + a queue put; encode/PUT/announce are the worker's.
        Never raises — staging is advisory."""
        t0 = time.perf_counter()
        try:
            with self._tracer.span(
                "shard_stage", cat="redundancy", step=self._step
            ):
                self._shard_stager.stage(self._step, self._manager_state_dict())
        except Exception:  # noqa: BLE001 — advisory plane
            self._bump_counter("shard_stage_failed")
            self._logger.exception("redundancy shard staging failed")
        self._record_timing("shard_stage_hot_s", time.perf_counter() - t0)

    # ---------------------------------------------------------- hot spare
    def promote(
        self, timeout: "float | timedelta | None" = None
    ) -> Dict[str, Any]:
        """Hot-spare promotion: block until the shard directory promotes
        this spare into the fleet (a member died), load the freshest
        prefetched state, and ONLY THEN join the control plane — create
        the rendezvous store, the ManagerServer (which heartbeats the
        lighthouse and so enters the next quorum), and the RPC clients.
        Returns the directory's promotion record. After this returns the
        Manager behaves exactly like one constructed with spare=False: the
        next start_quorum()/should_commit() cycle converges it bitwise
        (the prefetched generation IS a committed generation, so at worst
        one incremental heal covers the steps staged since)."""
        if not self._spare or self._hot_spare is None:
            raise RuntimeError("promote() requires Manager(spare=True)")
        budget = _to_seconds(timeout) if timeout is not None else None
        result = self._hot_spare.wait_promoted(timeout=budget)
        if result is None:
            raise TimeoutError(
                f"spare {self._replica_id} not promoted within {budget}s"
            )
        state_step, state, promotion = result
        self._spare_promotion = promotion
        if state is not None:
            with self._state_dict_lock.w_lock():
                user = state.get("user", {})
                for key, load_fn in self._load_state_dict_fns.items():
                    if key in user:
                        load_fn(user[key])
            self.load_state_dict(state["torchft"])
            self._logger.info(
                f"spare promoted at prefetched step {state_step} "
                f"(replacing {promotion.get('replaces')!r})"
            )
        else:
            self._logger.warning(
                "spare promoted with no prefetched generation — joining "
                "cold; the first quorum will heal it like any rejoiner"
            )
        self._hot_spare.shutdown()
        self._join_control_plane()
        # a promoted spare is a full member: it starts staging its own
        # shard generations like any group leader with the plane enabled
        if self._redundancy_cfg is not None and self._redundancy_cfg.enabled:
            try:
                from torchft_tpu import redundancy as _redundancy

                self._shard_stager = _redundancy.ShardStager(
                    self._redundancy_cfg,
                    self._replica_id,
                    on_metric=self._on_redundancy_metric,
                )
            except Exception:  # noqa: BLE001 — advisory plane
                self._logger.exception(
                    "promoted spare could not start its shard stager"
                )
        self._record_timing("spare_promote_step", float(state_step))
        return promotion

    def _join_control_plane(self) -> None:
        """The deferred half of __init__ for a spare: identical wiring to
        the group-leader branch, run at promotion time so the lighthouse
        only ever sees the spare once it is a real member."""
        args = self._spare_join_args
        assert args is not None, "control plane already joined"
        self._spare_join_args = None
        hostname = args["hostname"]
        store_addr = args["store_addr"]
        if store_addr is None:
            self._store = KvStoreServer("0.0.0.0:0")
            store_addr = f"{hostname}:{self._store.port}"
        lighthouse_addr = args["lighthouse_addr"]
        if lighthouse_addr is None:
            lighthouse_addr = os.environ[LIGHTHOUSE_ENV]
        bind_port = int(os.environ.get(MANAGER_PORT_ENV, 0))
        self._manager = ManagerServer(
            replica_id=self._replica_id,
            lighthouse_addr=lighthouse_addr,
            hostname=hostname,
            bind=f"0.0.0.0:{bind_port}",
            store_addr=store_addr,
            world_size=args["group_world_size"],
            heartbeat_interval=args["heartbeat_interval"],
            connect_timeout=self._connect_timeout,
            quorum_retries=args["quorum_retries"],
            aggregator_addr=os.environ.get(AGGREGATOR_ENV, ""),
        )
        manager_addr = self._manager.address()
        KvClient(store_addr, connect_timeout=self._connect_timeout).set(
            "manager_addr", manager_addr, timeout=self._timeout
        )
        self._store_addr = store_addr
        self._client = ManagerClient(
            manager_addr, connect_timeout=self._connect_timeout
        )
        self._vote_client = ManagerClient(
            manager_addr, connect_timeout=self._connect_timeout
        )
        self._client.set_retry_observer(self._on_rpc_retry)
        self._vote_client.set_retry_observer(self._on_rpc_retry)

    # -------------------------------------------------------- healthwatch
    def set_telemetry_transform(
        self, fn: Optional[Callable[[Dict[str, Any]], Dict[str, Any]]]
    ) -> None:
        """Install a hook applied to the per-step telemetry dict right
        before it is published to the lighthouse (None to clear). Exists
        for fault injection: ``EventInjector.slow_replica`` dilates the
        reported ``step_s`` so straggler ejection can be exercised without
        actually slowing a test replica down."""
        self._telemetry_transform = fn

    def _publish_step_telemetry(self, committed: bool = True) -> None:
        """Group leader only: stage this step's telemetry for the C++
        heartbeat thread (the lighthouse ingests it into the health
        ledger) and fold the summary the previous heartbeat brought back
        into timings() / the ``torchft_health`` stream.

        ``step_s`` is the wall clock between consecutive commit votes —
        the only boundary every replica crosses exactly once per step.
        ``wire_s`` is the most recent allreduce wire time, so the
        lighthouse can score on COMPUTE time (step minus wire): wall time
        equalizes across a quorum because the allreduce is a barrier, and
        the straggler is the replica whose compute share grew.

        A sample is published only when THIS vote and the PREVIOUS vote
        both committed AND both ran under the same quorum_id: a span
        touching a failed vote measures quorum retries, healing, or a
        discarded step, and a span crossing a reconfiguration measures the
        reconfiguration itself — neither is training pace. The quorum_id
        leg is what makes probationary readmission survivable: an excluded
        replica casts no votes at all while its quorum thread spins in the
        re-subscribe loop, so its first post-readmit interval bridges two
        committed votes that straddle the whole exclusion, and scoring
        that one multi-second sample would re-eject it on the spot.

        Must never raise — telemetry is advisory and this sits on the
        commit path."""
        self._tracer.set_context(step=self._step)
        if self._manager is None:
            return
        # fold the beat loop's latest skew estimate into the tracer so the
        # next export/auto-dump is merge-ready; pure local state, no RPC
        try:
            skew_fn = getattr(self._manager, "clock_skew", None)
            if skew_fn is not None:
                skew = skew_fn() or {}
                self._tracer.set_skew(
                    skew.get("skew_ms", 0.0),
                    skew.get("rtt_ms", 0.0),
                    skew.get("samples", 0),
                )
        except Exception:  # noqa: BLE001 — advisory plane, commit path
            pass
        now = time.perf_counter()
        last, self._last_commit_t = self._last_commit_t, now
        prev_committed = self._last_vote_committed
        self._last_vote_committed = committed
        same_quorum = self._quorum_id == self._telemetry_quorum_id
        self._telemetry_quorum_id = self._quorum_id
        try:
            if last is not None and committed and prev_committed and same_quorum:
                with self._metrics_lock:
                    wire_s = self._timings.get(
                        "allreduce_wire_s", self._timings.get("allreduce_s", 0.0)
                    )
                    heal_attempts = self._timings.get("heal_attempts", 0.0)
                    rpc_retries = self._timings.get("rpc_retries", 0.0)
                    reroutes = self._timings.get("collective_reroute", 0.0)
                    crc_fails = self._timings.get("chunk_crc_failures", 0.0)
                telemetry: Dict[str, Any] = {
                    "step": self._step,
                    "step_s": now - last,
                    "wire_s": wire_s,
                    "heal_attempts": heal_attempts,
                    "rpc_retries": rpc_retries,
                    # cumulative link-fault counters: the policy plane's
                    # link_quality signal differences these per replica
                    "collective_reroute": reroutes,
                    "chunk_crc_failures": crc_fails,
                }
                if self._degrade_cfg is not None:
                    # degrade plane: self-report capacity so the ledger
                    # scores this replica against what a step SHOULD cost
                    # at its current degree (healthwatch DEGRADED state)
                    with self._degrade_lock:
                        telemetry["group_world_size"] = self._group_degree
                        telemetry["full_group_world_size"] = (
                            self._full_group_degree
                        )
                if self._telemetry_transform is not None:
                    telemetry = self._telemetry_transform(telemetry)
                self._manager.publish_telemetry(telemetry)
            self._observe_health(self._manager.health())
        except Exception:  # noqa: BLE001 — advisory plane, commit path
            self._logger.exception("failed to publish step telemetry")

    def _observe_health(self, summary: Dict[str, Any]) -> None:
        """Fold a heartbeat health summary into timings() and emit a
        ``torchft_health`` event (plus a flight-recorder breadcrumb) on
        every state TRANSITION: ``straggler_warn`` on entering warn,
        ``eject`` on entering ejected, ``readmit`` on entering probation
        (the lighthouse lifts the exclusion at that edge), ``recovered``
        on returning to ok."""
        state = summary.get("state")
        if not state:
            return
        with self._metrics_lock:
            self._timings["health_state"] = float(summary.get("state_code", 0))
            self._timings["straggler_score"] = float(summary.get("score", 0.0))
            self._timings["ejections"] = float(summary.get("ejections", 0))
            self._timings["readmissions"] = float(summary.get("readmissions", 0))
        prev, self._last_health_state = self._last_health_state, state
        if prev == state or prev is None and state == "ok":
            return
        kind = {
            "warn": "straggler_warn",
            "ejected": "eject",
            "probation": "readmit",
            "ok": "recovered",
            # ledger acknowledged this replica's reduced group degree
            "degraded": "degrade_acked",
        }.get(state, state)
        emit_event_async(
            HEALTH_EVENTS,
            replica_id=self._replica_id,
            group_rank=self._group_rank,
            step=self._step,
            quorum_id=self._quorum_id,
            kind=kind,
            state=state,
            prev_state=prev,
            score=summary.get("score", 0.0),
            ejections=summary.get("ejections", 0),
            readmissions=summary.get("readmissions", 0),
        )
        self._logger.warning(
            f"healthwatch: {kind} (state {prev} -> {state}, "
            f"score={summary.get('score', 0.0)})"
        )
        from torchft_tpu.flight_recorder import recorder

        recorder.record(
            kind,
            state=state,
            prev_state=prev,
            score=summary.get("score", 0.0),
            step=self._step,
            replica=self._replica_id,
            group_rank=self._group_rank,
        )
        self._tracer.instant(
            kind,
            cat="health",
            state=state,
            prev_state=prev,
            score=summary.get("score", 0.0),
        )
        if kind == "eject":
            # the lighthouse just cut this replica out of the quorum: dump
            # both postmortem artifacts NOW, while the straggler evidence
            # (slow buckets, retried RPCs) is still in the rings
            fr_path = recorder.dump(
                reason="eject",
                quorum_id=self._quorum_id,
                tag=f"{self._replica_id}_{self._group_rank}"
                f"_s{self._step}_eject",
            )
            self._auto_dump_trace("eject", fr_path)

    def _log_timing_snapshot(self, phase: str) -> None:
        try:
            # through the bounded async drain: snapshots fire from the
            # commit path (which serializes with the trainer), so the JSON
            # encode + logging I/O must not ride the critical path
            emit_event_async(
                TIMING_EVENTS,
                replica_id=self._replica_id,
                group_rank=self._group_rank,
                step=self._step,
                quorum_id=self._quorum_id,
                phase=phase,
                **self.timings(),
            )
        except Exception:  # noqa: BLE001
            self._logger.exception("failed to log timing snapshot")

    # ------------------------------------------------------------- errors
    def report_error(self, e: Exception) -> None:
        """Mark the step as corrupt; it will be discarded at should_commit
        and the PG reconfigured on the next quorum."""
        # count error EPISODES, not report_error calls: one wire fault fans
        # out into a report per in-flight allreduce plus one per commit vote
        # while the PG stays errored — operators comparing this against
        # commit_failures need fault frequency, not callback fan-out. The
        # None-check and the assignment must be one atomic step: reports
        # arrive concurrently from allreduce done-callbacks and the timeout
        # loop, and two threads both observing None would double-count.
        with self._metrics_lock:
            if self._errored is None:
                self._metrics["errors"] += 1
            self._errored = ExceptionWithTraceback(e)
        from torchft_tpu.flight_recorder import recorder

        recorder.record(
            "manager_error",
            error=str(e),
            step=self._step,
            replica=self._replica_id,
            group_rank=self._group_rank,
        )
        recorder.dump(
            reason="manager_error",
            quorum_id=self._quorum_id,
            tag=f"{self._replica_id}_{self._group_rank}"
            f"_s{self._step}_manager_error",
        )
        log_error_event(
            replica_id=self._replica_id,
            group_rank=self._group_rank,
            step=self._step,
            quorum_id=self._quorum_id,
            error=str(e),
        )

    def errored(self) -> Optional[ExceptionWithTraceback]:
        return self._errored

    def wrap_future(
        self,
        fut: Future[T],
        default: Any,
        timeout: "float | timedelta | None" = None,
        arm_timeout: bool = True,
    ) -> Future[T]:
        """Timeout + swallow errors into ``default``, reporting them
        (reference: manager.py:516-558). ``default`` may be a zero-arg
        factory — then the fallback value is only built on the error path,
        not eagerly per call (a zeros pytree of a multi-GB gradient tree
        would otherwise cost host alloc + H2D on every healthy step).

        ``arm_timeout=False`` skips the submission-time timer for callers
        that arm their own deadline when work actually STARTS (the staged
        host path: a timer started at submission would charge queue time
        behind an in-flight quantized sync against this op)."""
        if arm_timeout:
            timed = future_timeout(
                fut,
                _to_seconds(timeout) if timeout is not None else self._timeout,
            )
        else:
            timed = fut

        def callback(f: Future[T]) -> T:
            try:
                return f.value()
            except Exception as e:  # noqa: BLE001
                self._logger.exception(f"got exception in future -- skipping remaining: {e}")
                self.report_error(e)
                return default() if callable(default) else default

        return timed.then(callback)

    # ------------------------------------------------------------- commit
    @traced("torchft::manager::should_commit")
    def should_commit(self, timeout: "float | timedelta | None" = None) -> bool:
        """Two-phase commit vote across the replica group; True iff every
        rank of this group is healthy and enough replicas participate
        (reference: manager.py:848-936)."""
        t_begin = time.perf_counter()
        # recovery (on the quorum thread) must finish before we decide
        if self._quorum_future is not None:
            try:
                self._quorum_future.result()
            except Exception as e:  # noqa: BLE001
                self.report_error(e)
        # time spent joining the quorum thread is overlap shortfall, not
        # bookkeeping — split it out so the steady-state budget is honest
        join_s = time.perf_counter() - t_begin

        # apply a pending backend swap BEFORE sampling pg.errored(): after
        # a membership change the OLD world is typically errored (the abort
        # that triggered the change); the sync flow cleared that state
        # inside configure, the split flow clears it at commit
        self._commit_pending_configure()
        # a staged intra-group degrade also lands here, BEFORE the errored
        # sample: the reshard replaces the dead member, so the step votes
        # as a re-planned slow step instead of a discarded one
        if self._degrade_cfg is not None:
            self._commit_pending_degrade()

        if (err := self._pg.errored()) is not None:
            self.report_error(err)

        self._sync_device_world()
        if self._healing and self._pending_state_dict is not None:
            self._apply_pending_state_dict()
        elif self._healing:
            # recovery failed mid-flight; the error is already reported and
            # this step will not commit — retry healing on the next quorum
            self._healing = False

        enough_replicas = self.num_participants() >= self._min_replica_size
        local_should_commit = enough_replicas and self._errored is None
        if not local_should_commit:
            # a false local vote silently discards the whole group's step —
            # at WARNING so the reason is visible under default logging
            # (INFO-only reasons made a spurious device-plane error during
            # a quiet chaos soak undiagnosable from its console log)
            self._logger.warning(
                f"voting False for step {self._step}: "
                f"enough_replicas={enough_replicas} "
                f"(participants={self.num_participants()} "
                f"min={self._min_replica_size}) "
                f"errored={self._errored!r}"
            )
        # the vote rides its own warm client (see __init__) and a pre-built
        # frame (coordination.py): the steady-state step is this one RPC
        # round-trip plus the collective
        t_rpc = time.perf_counter()
        with self._tracer.span(
            "commit_vote", cat="commit", local=local_should_commit
        ):
            should_commit = self._vote_client.should_commit(
                self._group_rank,
                self._step,
                local_should_commit,
                timeout=_to_seconds(timeout) if timeout is not None else self._timeout,
            )
        rpc_s = time.perf_counter() - t_rpc
        # per-step outcome at DEBUG: the False cases already warn above /
        # in the retry path, and the commit event below carries the full
        # record — an INFO line per healthy step is pure hot-loop cost
        self._logger.debug(
            f"should_commit={should_commit} enough_replicas={enough_replicas} errored={self._errored is not None}"
        )
        emit_event_async(
            COMMIT_EVENTS,
            replica_id=self._replica_id,
            group_rank=self._group_rank,
            step=self._step,
            quorum_id=self._quorum_id,
            committed=should_commit,
            enough_replicas=enough_replicas,
            errored=self._errored is not None,
            num_participants=self.num_participants(),
        )

        if not self._standby_source:
            self._checkpoint_transport.disallow_checkpoint()

        if should_commit:
            if self._serve_publisher is not None:
                # publish the committed snapshot BEFORE the step advances:
                # the serving version is stamped with the step that voted
                self._serve_publish_committed()
            if self._shard_stager is not None:
                # redundancy plane: arm staging for the NEXT round start.
                # Staging here would label the generation with the step
                # that just voted, but a healer joining round M needs the
                # post-commit state labeled M — which only exists once the
                # caller applies this round's update. Deferring to
                # start_quorum also lands the announce BEFORE the round's
                # allreduce barrier, so a healer blocking that barrier can
                # still reconstruct the generation it needs.
                self._redundancy_stage_pending = True
            self._step += 1
            self._batches_committed += self.num_participants()
            self._commit_failures = 0
            self._bump_metric("commits")
        else:
            self._commit_failures += 1
            self._bump_metric("commit_failures")
            if (
                self._max_retries is not None
                and self._commit_failures > self._max_retries
            ):
                msg = (
                    f"should_commit failed {self._commit_failures} times "
                    f"consecutively, exceeding max_retries={self._max_retries}"
                )
                self._logger.exception(msg)
                raise RuntimeError(msg)

        self._record_timing("should_commit_rpc_s", rpc_s)
        self._record_timing(
            "bookkeeping_s",
            max(0.0, time.perf_counter() - t_begin - rpc_s - join_s),
        )
        # stage telemetry for the heartbeat thread + fold back the health
        # summary it last brought home; pure bookkeeping (one dict build
        # and two lock hops), no RPC on this path
        self._publish_step_telemetry(committed=should_commit)
        return should_commit

    # -------------------------------------------------------- introspection
    def load_state_dict(self, state_dict: Dict[str, int]) -> None:
        self._step = state_dict["step"]
        self._batches_committed = state_dict["batches_committed"]

    def _manager_state_dict(self) -> Dict[str, Any]:
        assert len(self._user_state_dicts) > 0, "user state_dict is not initialized"
        # one source of truth for the user-state composition: live healing
        # and durable checkpoints must capture the same composite
        return {
            "user": self.user_state_dict(),
            "torchft": self.state_dict(),
        }

    def state_dict(self) -> Dict[str, int]:
        """Manager state for durable checkpoints: include this in your own
        periodic checkpoints (reference: manager.py:938-958)."""
        return {"step": self._step, "batches_committed": self._batches_committed}

    def state_dict_template(self) -> Dict[str, Any]:
        """The LIVE healing composite, for use as a PGTransport in-place
        template: ``PGTransport(pg, state_dict_template=lambda:
        manager.state_dict_template())`` (late-bound — construct the
        transport first, the Manager after). Because sender and receiver
        both build this exact tree from their registered state-dict fns,
        the transport's index-based leaf alignment holds by construction —
        including algorithm state like DiLoCo fragments, whose keys sort
        BEFORE "default" in the flattened composite (hand-rolled templates
        that guess the shape silently lose the in-place property when any
        extra state fn is registered)."""
        return self._manager_state_dict()

    def user_state_dict(self) -> Dict[str, Any]:
        """Every registered user state (trainer state, DiLoCo fragment
        globals + outer optimizer, LocalSGD backups, data position, ...)
        under the read lock — the same composite live healing transfers.
        Durable (tier-2) checkpoints should save THIS, not just the
        trainer's own state, or algorithm state silently resets on a cold
        restart."""
        with self._state_dict_lock.r_lock():
            return {key: fn() for key, fn in self._user_state_dicts.items()}

    def load_user_state_dict(self, user_state: Dict[str, Any]) -> None:
        """Feed a ``user_state_dict()`` composite back through every
        registered load fn (the cold-restart counterpart of healing's
        ``_apply_pending_state_dict``)."""
        with self._state_dict_lock.w_lock():
            for key, load_fn in self._load_state_dict_fns.items():
                if key in user_state:
                    load_fn(user_state[key])

    def current_quorum_id(self) -> int:
        """The id of the last quorum this manager joined (-1 before the
        first). Bumps exactly when the lighthouse changes membership (or
        after commit failures) — operators and benchmarks use the bump as
        the observable 'membership changed' edge."""
        return self._quorum_id

    def current_step(self) -> int:
        return self._step

    def batches_committed(self) -> int:
        return self._batches_committed

    def participating_rank(self) -> Optional[int]:
        if self._quorum_future is None:
            return None
        self.wait_quorum()
        return self._participating_replica_rank

    # aliases used by wrappers
    def replica_rank(self) -> Optional[int]:
        return self.participating_rank()

    def num_participants(self) -> int:
        if self._quorum_future is None:
            return 0
        self.wait_quorum()
        assert self._participating_replica_world_size >= 0
        return self._participating_replica_world_size

    def num_replicas(self) -> int:
        """Total replicas in the current quorum, including non-participants."""
        return self._num_replicas

    def is_participating(self) -> bool:
        if self._participating_replica_rank is None:
            return False
        if self._healing:
            assert self._use_async_quorum
            return False
        return True

    def last_quorum_healed(self) -> bool:
        """True iff the most recent quorum live-healed this replica (its
        registered state-dict fns were fed recovered state). Functional
        training loops use this to re-read state that the quorum rebound —
        values captured before ``start_quorum`` are stale after a heal."""
        return self._last_quorum_healed

    # ------------------------------------------------------------ lifecycle
    def shutdown(self, wait: bool = True) -> None:
        if self._metrics_server is not None:
            self._metrics_server.shutdown()
            self._metrics_server = None
        # redundancy plane first: its worker threads hold no locks the
        # teardown below needs, and a late shard PUT against a dying peer
        # is harmless but noisy
        if self._shard_stager is not None:
            try:
                self._shard_stager.shutdown()
            except Exception:  # noqa: BLE001 — teardown must never raise
                pass
            self._shard_stager = None
        if self._hot_spare is not None:
            try:
                self._hot_spare.shutdown()
            except Exception:  # noqa: BLE001 — teardown must never raise
                pass
            self._hot_spare = None
        self._checkpoint_transport.shutdown(wait=wait)
        if self._manager is not None:
            self._manager.shutdown()
        if self._store is not None:
            self._store.shutdown()
        self._executor.shutdown(wait=wait)
        # never apply a backend swap during teardown — drop it
        with self._pending_commit_lock:
            self._pending_pg_commit = None
        # cancel queued (not-yet-run) staging tasks on a non-waiting
        # shutdown: they would otherwise dispatch against the PG after
        # pg.shutdown below, spuriously reporting errors on a torn-down
        # manager — and fail their staged futures so any waiter unblocks
        # immediately instead of riding out the full timeout
        with self._staged_lock:
            self._staging_down = True
        self._staging_executor.shutdown(wait=wait, cancel_futures=not wait)
        # pipeline unpack worker: cancelled bucket unpacks leave their
        # bucket futures unresolved — the aggregate is bounded by the stage
        # watchdog / sweep below, so no waiter stalls past the timeout
        self._unpack_executor.shutdown(wait=wait, cancel_futures=not wait)
        with self._staged_lock:
            pending, self._staged_pending = self._staged_pending, []
        for exec_fut, staged_fut in pending:
            if exec_fut.cancelled() and not staged_fut.done():
                try:
                    staged_fut.set_exception(
                        RuntimeError("manager shut down before dispatch")
                    )
                except RuntimeError:
                    pass
        self._pg.shutdown()
        # best-effort: land any commit/timing events still queued in the
        # async drain before the process (and its log handlers) go away
        try:
            from torchft_tpu.observability import get_event_drain

            get_event_drain().flush(timeout=2.0)
        except Exception:  # noqa: BLE001 — teardown must never raise
            pass

    @property
    def store_addr(self) -> str:
        """Rendezvous store address of this replica group (leader's store)."""
        assert self._store_addr is not None
        return self._store_addr


def _np_dtype(x: Any) -> Any:
    return np.asarray(x).dtype if not hasattr(x, "dtype") else x.dtype


def _is_float_dtype(dtype: Any) -> bool:
    """True for dtypes the wire codecs can compress (incl. ml_dtypes
    bfloat16, which numpy does not class as np.floating)."""
    return bool(
        np.issubdtype(np.dtype(dtype), np.floating)
        or "bfloat16" in str(dtype)
    )


def _covered_seconds(
    start: float, end: float, intervals: List[Any]
) -> float:
    """Length of ``[start, end]`` covered by the union of ``intervals``."""
    if end <= start:
        return 0.0
    clipped = sorted(
        (max(start, a), min(end, b))
        for a, b in intervals
        if b > start and a < end
    )
    total = 0.0
    cur_s: Optional[float] = None
    cur_e = 0.0
    for a, b in clipped:
        if cur_s is None:
            cur_s, cur_e = a, b
        elif a <= cur_e:
            cur_e = max(cur_e, b)
        else:
            total += cur_e - cur_s
            cur_s, cur_e = a, b
    if cur_s is not None:
        total += cur_e - cur_s
    return total


def _pipeline_overlap_stats(marks: List[Dict[str, Any]]) -> Dict[str, float]:
    """Summarize one streamed allreduce's per-bucket stage marks.

    ``marks[i]`` maps stage name (``pack`` / ``wire`` / ``unpack``) to a
    ``(start, end)`` perf_counter interval; stages a bucket never reached
    (mid-stream failure, timeout) are simply absent. ``overlap_efficiency``
    is Σᵢ |wireᵢ ∩ ∪ⱼ≠ᵢ(packⱼ ∪ wireⱼ ∪ unpackⱼ)| / Σᵢ |wireᵢ| — the
    fraction of wire time hidden behind other buckets' pipeline stages
    (a lower bound: overlap with caller compute is not observable here).
    A single-bucket plan has nothing to hide behind and reports 0.0."""
    pack_s = sum(e - s for m in marks if "pack" in m for s, e in [m["pack"]])
    wire_s = sum(e - s for m in marks if "wire" in m for s, e in [m["wire"]])
    unpack_s = sum(
        e - s for m in marks if "unpack" in m for s, e in [m["unpack"]]
    )
    hidden = 0.0
    for i, m in enumerate(marks):
        if "wire" not in m:
            continue
        s, e = m["wire"]
        others = [
            iv
            for j, mj in enumerate(marks)
            if j != i
            for iv in mj.values()
        ]
        hidden += _covered_seconds(s, e, others)
    return {
        "allreduce_pack_s": pack_s,
        "allreduce_wire_s": wire_s,
        "allreduce_unpack_s": unpack_s,
        "allreduce_buckets": float(len(marks)),
        "overlap_efficiency": (hidden / wire_s) if wire_s > 0 else 0.0,
    }
