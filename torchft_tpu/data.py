"""Data sharding across an elastic replica fleet.

Role-equivalent of the reference DistributedSampler (torchft/data.py:24-77):
shards a dataset across ``num_replica_groups x group_world_size`` workers,
where the global shard index is
``group_rank + group_world_size * replica_rank``. Lossy by design — on
membership change replicas keep their static shard, trading some
over/under-sampling for zero resharding cost (reference docstring data.py:7-22).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

__all__ = ["DistributedSampler", "StatefulDataIterator", "shard_indices"]


def shard_indices(
    num_samples: int,
    group_rank: int,
    replica_rank: int,
    group_world_size: int = 1,
    num_replica_groups: int = 1,
) -> tuple[int, int]:
    """Return this worker's (global_rank, total_shards)."""
    global_rank = group_rank + group_world_size * replica_rank
    total = group_world_size * num_replica_groups
    assert 0 <= global_rank < total, (global_rank, total)
    return global_rank, total


class DistributedSampler:
    """Epoch-shuffled index sampler over this worker's shard.

    Iterates indices ``i`` with ``i % total == global_rank`` after an
    epoch-seeded shuffle, like torch's DistributedSampler contract.
    """

    def __init__(
        self,
        num_samples: int,
        group_rank: int,
        replica_rank: int,
        group_world_size: int = 1,
        num_replica_groups: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ) -> None:
        self._num_samples = num_samples
        self.global_rank, self.total_shards = shard_indices(
            num_samples, group_rank, replica_rank, group_world_size, num_replica_groups
        )
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0
        self._drop_last = drop_last

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        if self._drop_last:
            return self._num_samples // self.total_shards
        return (self._num_samples + self.total_shards - 1) // self.total_shards

    def __iter__(self) -> Iterator[int]:
        order = np.arange(self._num_samples)
        if self._shuffle:
            rng = np.random.RandomState(self._seed + self._epoch)
            rng.shuffle(order)
        n = len(self) * self.total_shards
        if not self._drop_last and n > self._num_samples:
            # pad by tiling, so every shard has equal length even when the
            # dataset is smaller than the shard count
            order = np.resize(order, n)
        else:
            order = order[:n]
        yield from order[self.global_rank :: self.total_shards].tolist()


class StatefulDataIterator:
    """Resumable iteration over a DistributedSampler.

    The reference recommends torchdata's StatefulDataLoader so the data
    position rides along in checkpoints (data.py:7-14, train_ddp.py); this is
    the in-tree equivalent: ``state_dict()/load_state_dict()`` capture
    (epoch, offset) and belong in the state registered with the Manager so a
    healed replica resumes from the same batch position as its recovery
    source. Epochs advance automatically when a shard is exhausted.
    """

    def __init__(self, sampler: DistributedSampler) -> None:
        self._sampler = sampler
        self._epoch = 0
        self._offset = 0
        self._cache_epoch: Optional[int] = None
        self._cache: list = []

    def state_dict(self) -> dict:
        return {"epoch": self._epoch, "offset": self._offset}

    def load_state_dict(self, sd: dict) -> None:
        self._epoch = int(sd["epoch"])
        self._offset = int(sd["offset"])

    def _shard(self) -> list:
        if self._cache_epoch != self._epoch:
            self._sampler.set_epoch(self._epoch)
            self._cache = list(self._sampler)
            self._cache_epoch = self._epoch
        return self._cache

    def __iter__(self) -> "StatefulDataIterator":
        return self

    def __next__(self) -> int:
        shard = self._shard()
        if self._offset >= len(shard):
            self._epoch += 1
            self._offset = 0
            shard = self._shard()
            if not shard:
                raise ValueError(
                    "sampler shard is empty (num_samples < total shards with "
                    "drop_last=True); nothing to iterate"
                )
        idx = shard[self._offset]
        self._offset += 1
        return int(idx)
