"""Standalone lighthouse-aggregator CLI (two-level control plane).

Run one aggregator per pod of replicas::

    python -m torchft_tpu.aggregator --root http://roothost:29510 \
        --bind 0.0.0.0:29520

Pod workers point at it via ``TORCHFT_LIGHTHOUSE_AGGREGATOR=host:29520``
(keeping ``TORCHFT_LIGHTHOUSE`` set to the root for failover) — the manager
speaks the same wire protocol to an aggregator as to a lighthouse, so no
other configuration changes. Upstream, the aggregator batches the whole
pod's heartbeats/telemetry into one delta-encoded ``agg_tick`` RPC per tick
and fans quorum results back out. The same port serves ``GET /status``
JSON (pod size / live set / upstream tick counters).

Sizing rule of thumb: one aggregator per 32-64 replicas keeps both the pod
fan-in and the root's aggregator count comfortable (see
docs/operations.md, "Running a fleet").
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

from torchft_tpu.coordination import AggregatorServer

# Managers read this to point control RPCs at a pod aggregator (manager.py
# re-exports it as AGGREGATOR_ENV).
AGGREGATOR_ENV = "TORCHFT_LIGHTHOUSE_AGGREGATOR"


def main(argv: "list[str] | None" = None) -> None:
    parser = argparse.ArgumentParser(
        prog="torchft_tpu_aggregator", description=__doc__
    )
    parser.add_argument(
        "--root",
        required=True,
        help="root lighthouse address (host:port; http:// prefix tolerated)",
    )
    parser.add_argument("--bind", default="0.0.0.0:29520")
    parser.add_argument(
        "--agg-id", "--agg_id", default="", help="stable aggregator id "
        "(default: derived from the bind address)"
    )
    parser.add_argument(
        "--tick-ms", "--tick_ms", type=int, default=100,
        help="upstream batching cadence (one agg_tick RPC per tick)",
    )
    parser.add_argument(
        "--heartbeat-timeout-ms", "--heartbeat_timeout_ms", type=int,
        default=5000, help="pod-liveness horizon; match the root lighthouse",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    server = AggregatorServer(
        root_addr=args.root,
        bind=args.bind,
        agg_id=args.agg_id,
        tick_ms=args.tick_ms,
        heartbeat_timeout_ms=args.heartbeat_timeout_ms,
    )
    logging.info("aggregator listening at %s (root %s)", server.address(), args.root)

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.shutdown()


if __name__ == "__main__":
    main()
