"""Fleet trace tooling CLI.

Merge N replicas' span dumps (written by ``SpanRecorder.dump`` — auto on
heal_exhausted/eject next to the flight-recorder dump, or on demand via
``Manager.dump_trace``) into one skew-corrected Chrome-trace JSON:

    python -m torchft_tpu.trace merge fleet.json dump_r0.json dump_r1.json ...

Globs work through the shell; open the output in Perfetto
(https://ui.perfetto.dev) or chrome://tracing. Each replica renders as a
process row (labelled with its estimated clock skew vs the lighthouse) and
each span category (quorum / commit / heal / allreduce / rpc) as a thread
row; all timestamps sit on the lighthouse's clock.

Also summarizes a recorded-history JSONL (the lighthouse's
``history_path`` store) through the canonical Python fold:

    python -m torchft_tpu.trace history lighthouse_history.jsonl

See docs/observability.md for the span taxonomy and the slow-step runbook.
"""

from __future__ import annotations

import json
import sys
from typing import List

from torchft_tpu.tracing import history_fold, load_history, merge_traces


def _usage() -> int:
    sys.stderr.write(
        "usage: python -m torchft_tpu.trace merge OUT.json DUMP.json"
        " [DUMP.json ...]\n"
        "       python -m torchft_tpu.trace history HISTORY.jsonl\n"
    )
    return 2


def main(argv: List[str]) -> int:
    if not argv:
        return _usage()
    cmd, args = argv[0], argv[1:]
    if cmd == "merge":
        if len(args) < 2:
            return _usage()
        out_path, dump_paths = args[0], args[1:]
        dumps = []
        for p in dump_paths:
            with open(p) as f:
                dumps.append(json.load(f))
        trace = merge_traces(dumps)
        with open(out_path, "w") as f:
            json.dump(trace, f)
        n_spans = sum(len(d.get("spans", [])) for d in dumps)
        print(
            f"merged {len(dumps)} replica dumps / {n_spans} spans "
            f"-> {out_path}"
        )
        return 0
    if cmd == "history":
        if len(args) != 1:
            return _usage()
        # load_history sniffs gzip and accepts content too, so this CLI and
        # coordination.history_replay share one loader (they diverged once:
        # path-only plain-text here vs content-only there).
        events = load_history(args[0])
        print(json.dumps(history_fold(events), indent=2, sort_keys=True))
        return 0
    return _usage()


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
