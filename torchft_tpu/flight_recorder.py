"""Flight recorder: in-memory ring buffer of recent FT events, dumped to
disk on aborts for postmortem debugging.

The reference integrates NCCL's Flight Recorder: per-quorum dump paths
``{base}_quorum_{id}/{global_rank}`` (manager.py:808-817), recorder state
reset after reconfigure (manager.py:729-733), and abort-triggered dumps
through a named pipe (process_group.py:87-106, 879-883). XLA has no
equivalent runtime recorder, so this module *is* the recorder: hot paths
append cheap dict records (collective submit/complete, quorum transitions,
timeouts, aborts) into a bounded deque, and ``dump()`` — called from
``ProcessGroup.abort()`` and fatal manager errors when
``TORCHFT_FR_BASE_PATH`` is set — writes the ring as JSON lines.

One recorder is shared per process. Multiple replica-group Managers may run
in one process (the thread-based test topology), so dump *identity* is the
caller's: ``dump(reason, quorum_id=..., tag=...)`` takes the dumping
replica's coordinates rather than reading mutable singleton state, and
events carry whatever identifying fields the recording site passes.

Thread-safe; recording is O(1) append of already-built dicts, no I/O.

Resilient-heal instrumentation rides the same ring: the Manager records
``heal_retry`` / ``heal_failover`` / ``chunk_crc_failure`` as the
checkpoint transport reports them and ``rpc_retry`` per retried
control-plane call, and dumps with ``reason="heal_exhausted"`` when a heal
runs out of candidate peers — so the dump contains the full retry/failover
sequence that led to the abort.

Healthwatch transitions ride it too: the Manager records
``straggler_warn`` / ``eject`` / ``readmit`` / ``recovered`` as it observes
its own state change in heartbeat health summaries (manager.py
``_observe_health``), so a postmortem dump shows whether the replica was
warned or proactively excluded before the failure being debugged.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Deque, Dict, Optional

FR_BASE_PATH_ENV = "TORCHFT_FR_BASE_PATH"
FR_CAPACITY_ENV = "TORCHFT_FR_CAPACITY"

_DEFAULT_CAPACITY = 2048

__all__ = ["FlightRecorder", "recorder"]


def _env_capacity() -> int:
    raw = os.environ.get(FR_CAPACITY_ENV, "")
    try:
        cap = int(raw)
        return max(16, cap)
    except ValueError:
        # a bad observability knob must never break training
        return _DEFAULT_CAPACITY


class FlightRecorder:
    def __init__(self, capacity: Optional[int] = None) -> None:
        cap = capacity if capacity is not None else _env_capacity()
        self._events: Deque[Dict[str, Any]] = deque(maxlen=cap)
        self._lock = threading.Lock()
        self._seq = 0

    def record(self, kind: str, **fields: Any) -> None:
        with self._lock:
            self._seq += 1
            self._events.append(
                {"seq": self._seq, "time": time.time(), "kind": kind, **fields}
            )

    def dump_path(
        self, quorum_id: "int | str | None" = None, tag: Optional[str] = None
    ) -> Optional[Path]:
        base = os.environ.get(FR_BASE_PATH_ENV)
        if not base:
            return None
        qid = quorum_id if quorum_id is not None else "unknown"
        return Path(f"{base}_quorum_{qid}") / (tag or str(os.getpid()))

    def dump(
        self,
        reason: str = "abort",
        quorum_id: "int | str | None" = None,
        tag: Optional[str] = None,
    ) -> Optional[Path]:
        """Write the ring to ``{base}_quorum_{quorum_id}/{tag}``; returns the
        path or None when disabled. Never raises (dump runs on failure
        paths)."""
        try:
            # unique per dump — explicit tags included: two dumps with the
            # same tag in one process (e.g. repeated manager_errors) must
            # not overwrite each other's postmortem evidence
            with self._lock:
                self._dump_seq = getattr(self, "_dump_seq", 0) + 1
                seq = self._dump_seq
            base_tag = tag if tag is not None else str(os.getpid())
            tag = f"{base_tag}_{seq}"
            path = self.dump_path(quorum_id, tag)
            if path is None:
                return None
            self.record("dump", reason=reason)
            path.parent.mkdir(parents=True, exist_ok=True)
            with self._lock:
                events = list(self._events)
            with open(path, "w") as f:
                for e in events:
                    f.write(json.dumps(e, default=str) + "\n")
            return path
        except Exception:  # noqa: BLE001
            return None


# process-wide singleton, like the reference's per-process FR
recorder = FlightRecorder()
