"""Shared pytree bucketing for the managed data plane.

One bucketing implementation for every consumer — ``Manager.allreduce``,
``ddp.PureDistributedDataParallel``, and DiLoCo's fragment sync
(local_sgd.py) — so a pytree of hundreds of leaves becomes a handful of
flat same-dtype collectives on both the host ring and the XLA plane.
Fewer, larger collectives amortize the per-op framing/pickling overhead of
the host DCN plane — the same motivation as the reference's bucketized
allreduce (local_sgd.py:498-566), minus the NCCL-launch angle which does
not exist on TPU.

Three pieces keep the steady-state step allocation-free:

- :func:`plan_for` — a cached flatten plan (:class:`BucketPlan`): bucket
  membership and unpack metadata are a pure function of the tree structure
  and the leaves' shapes/dtypes, so they are computed once per (treedef,
  leaf-spec, cap) and memoized. A training loop that allreduces the same
  gradient tree every step pays the grouping cost exactly once.
- :class:`BufferPool` — reusable host staging buffers keyed by
  (dtype, size). Host-plane packs write into a recycled buffer instead of
  allocating a gradient-sized array per step.
- :func:`pack` / :func:`unpack` — bucket materialization. Groups whose
  leaves are all ``jax.Array`` pack on device (one fused concatenate, async
  dispatch, no host round-trip — and the fresh buffer doubles as the
  donation-safe capture the Manager's staging path needs); any other group
  packs into a (pooled) numpy buffer.

Bucketing is bitwise-transparent: an allreduce is elementwise across
replicas, so packing leaves into flat buffers changes neither the reduction
order per element nor the dtype — the DiLoCo regression fixtures stay
bitwise green with it on or off.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "DEFAULT_BUCKET_CAP_BYTES",
    "BucketPlan",
    "BufferPool",
    "build_plan",
    "plan_for",
    "pack",
    "unpack",
    "unpack_bucket",
    "make_buckets",
    "pack_group",
    "unpack_buckets",
]

# 1 GiB default bucket cap (reference: local_sgd.py:176)
DEFAULT_BUCKET_CAP_BYTES = 1 << 30

# metas entry: (leaf_index, offset_elems, size_elems, shape)
Meta = Tuple[int, int, int, Tuple[int, ...]]


def _leaf_dtype(leaf: Any) -> np.dtype:
    """Leaf dtype without forcing a device→host transfer (jax.Array and
    ml_dtypes dtypes pass through np.dtype unchanged)."""
    dt = getattr(leaf, "dtype", None)
    if dt is not None:
        return np.dtype(dt)
    return np.asarray(leaf).dtype


def _leaf_size(leaf: Any) -> int:
    size = getattr(leaf, "size", None)
    if size is not None:
        return int(size)
    return int(np.asarray(leaf).size)


def _leaf_shape(leaf: Any) -> Tuple[int, ...]:
    shape = getattr(leaf, "shape", None)
    if shape is not None:
        return tuple(shape)
    return tuple(np.shape(leaf))


class BucketPlan:
    """Bucket membership + unpack metadata for one leaf list.

    A plan is a pure function of the leaves' (shape, dtype) sequence and the
    cap — it holds no array data, so one plan serves every step of a
    training loop over the same tree.
    """

    # __weakref__ lets the Manager key per-bucket error-feedback residuals
    # by plan identity (WeakKeyDictionary): residuals die with the plan when
    # the plan cache evicts, instead of leaking per-tree forever
    __slots__ = (
        "groups", "metas", "sizes", "dtypes", "num_leaves", "cap_bytes",
        "__weakref__",
    )

    def __init__(
        self,
        groups: List[List[int]],
        metas: List[List[Meta]],
        sizes: List[int],
        dtypes: List[np.dtype],
        num_leaves: int,
        cap_bytes: int,
    ) -> None:
        self.groups = groups
        self.metas = metas
        self.sizes = sizes  # flat element count per bucket
        self.dtypes = dtypes  # dtype per bucket
        self.num_leaves = num_leaves
        self.cap_bytes = cap_bytes

    def __len__(self) -> int:
        return len(self.groups)


def build_plan(leaves: Sequence[Any], cap_bytes: int) -> BucketPlan:
    """Group leaf indices into flat same-dtype buckets of at most
    ``cap_bytes`` (a single leaf above the cap gets its own bucket)."""
    by_dtype: Dict[np.dtype, List[int]] = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(_leaf_dtype(leaf), []).append(i)
    groups: List[List[int]] = []
    dtypes: List[np.dtype] = []
    for dtype, idxs in by_dtype.items():
        itemsize = dtype.itemsize
        cur: List[int] = []
        cur_bytes = 0
        for i in idxs:
            nbytes = _leaf_size(leaves[i]) * itemsize
            if cur and cur_bytes + nbytes > cap_bytes:
                groups.append(cur)
                dtypes.append(dtype)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nbytes
        if cur:
            groups.append(cur)
            dtypes.append(dtype)
    metas: List[List[Meta]] = []
    sizes: List[int] = []
    for g in groups:
        offset = 0
        group_metas: List[Meta] = []
        for i in g:
            size = _leaf_size(leaves[i])
            group_metas.append((i, offset, size, _leaf_shape(leaves[i])))
            offset += size
        metas.append(group_metas)
        sizes.append(offset)
    return BucketPlan(groups, metas, sizes, dtypes, len(leaves), cap_bytes)


# plan cache: key -> BucketPlan. Bounded by wholesale clear — a trainer
# touches a handful of distinct trees, and rebuilding a plan is cheap; the
# cache exists to take the O(leaves) grouping off EVERY step, not to be an
# LRU.
_plan_cache: Dict[Any, BucketPlan] = {}
_plan_cache_lock = threading.Lock()
_PLAN_CACHE_MAX = 128


def plan_for(
    leaves: Sequence[Any], cap_bytes: int, treedef: Any = None
) -> BucketPlan:
    """Memoized :func:`build_plan`, keyed by (treedef, leaf specs, cap).

    ``treedef`` (hashable, from ``jax.tree_util.tree_flatten``) keys the
    tree identity; the (shape, dtype) spec guards against a same-structure
    tree with different leaf geometry sharing a plan.
    """
    try:
        spec = tuple((str(_leaf_dtype(l)), _leaf_shape(l)) for l in leaves)
        key = (treedef, spec, cap_bytes)
        with _plan_cache_lock:
            plan = _plan_cache.get(key)
        if plan is not None:
            return plan
    except TypeError:  # unhashable treedef — build uncached
        return build_plan(leaves, cap_bytes)
    plan = build_plan(leaves, cap_bytes)
    with _plan_cache_lock:
        if len(_plan_cache) >= _PLAN_CACHE_MAX:
            _plan_cache.clear()
        _plan_cache[key] = plan
    return plan


class BufferPool:
    """Reusable 1-D host staging buffers keyed by (dtype, size).

    ``acquire`` returns a recycled buffer when one is free, else allocates;
    ``release`` returns a buffer for reuse. The pool caps how many buffers
    it retains per key so a one-off giant tree can't pin memory forever.
    Thread-safe: acquire/release may run on the train loop and the
    Manager's staging worker concurrently.
    """

    def __init__(self, max_per_key: int = 4) -> None:
        self._lock = threading.Lock()
        self._free: Dict[Tuple[str, int], List[np.ndarray]] = {}
        self._max_per_key = max_per_key
        self.hits = 0
        self.misses = 0

    def acquire(self, size: int, dtype: Any) -> np.ndarray:
        dtype = np.dtype(dtype)
        key = (dtype.str, int(size))
        with self._lock:
            bucket = self._free.get(key)
            if bucket:
                self.hits += 1
                return bucket.pop()
            self.misses += 1
        return np.empty(int(size), dtype=dtype)

    def release(self, buf: np.ndarray) -> None:
        if not isinstance(buf, np.ndarray) or buf.ndim != 1:
            return
        key = (buf.dtype.str, buf.shape[0])
        with self._lock:
            bucket = self._free.setdefault(key, [])
            if len(bucket) < self._max_per_key:
                bucket.append(buf)


def pack(
    leaves: Sequence[Any],
    plan: BucketPlan,
    pool: Optional[BufferPool] = None,
) -> Tuple[List[Any], List[np.ndarray]]:
    """Materialize the plan's buckets from ``leaves``.

    Returns ``(flats, pooled)``: one flat buffer per bucket, plus the
    subset of ``flats`` that came from ``pool`` (the caller releases those
    back once the collective has resolved). Device groups (all leaves
    ``jax.Array``) concatenate on device — a fresh buffer, so it is safe
    against the caller's next donating jit step; host groups copy into a
    pooled (or fresh) numpy buffer, which is likewise a private capture.
    """
    import jax

    flats: List[Any] = []
    pooled: List[np.ndarray] = []
    for g, metas, size, dtype in zip(plan.groups, plan.metas, plan.sizes, plan.dtypes):
        if all(isinstance(leaves[i], jax.Array) for i in g):
            import jax.numpy as jnp

            if len(g) == 1:
                # single-leaf bucket: reshape is a view-like device op, but
                # the Manager's staging contract needs a private buffer —
                # copy explicitly
                flat = jnp.copy(leaves[g[0]]).reshape(-1)
            else:
                flat = jnp.concatenate(
                    [leaves[i].reshape(-1) for i in g]
                )
        else:
            if pool is not None:
                flat = pool.acquire(size, dtype)
                pooled.append(flat)
            else:
                flat = np.empty(size, dtype=dtype)
            for (i, off, n, _shape) in metas:
                flat[off : off + n] = np.asarray(leaves[i]).reshape(-1)
        flats.append(flat)
    return flats, pooled


def unpack(flats: Sequence[Any], plan: BucketPlan) -> List[Any]:
    """Slice the reduced flat buckets back into per-leaf arrays (views for
    numpy flats, lazy device slices for jax flats), in leaf order."""
    import jax

    out: List[Optional[Any]] = [None] * plan.num_leaves
    for flat, metas in zip(flats, plan.metas):
        if not isinstance(flat, jax.Array):
            flat = np.asarray(flat)
        for (i, off, size, shape) in metas:
            out[i] = flat[off : off + size].reshape(shape)
    assert all(o is not None for o in out)
    return out  # type: ignore[return-value]


def unpack_bucket(flat: Any, plan: BucketPlan, bucket: int) -> List[Tuple[int, Any]]:
    """Slice ONE reduced bucket into ``(leaf_index, array)`` pairs.

    The streaming pipeline unpacks each bucket as its wire completes instead
    of waiting for the whole plan; slices are views (numpy) or lazy device
    slices (jax), exactly as :func:`unpack` produces for that bucket.
    """
    import jax

    if not isinstance(flat, jax.Array):
        flat = np.asarray(flat)
    return [
        (i, flat[off : off + size].reshape(shape))
        for (i, off, size, shape) in plan.metas[bucket]
    ]


# ---------------------------------------------------------------------------
# list-of-(flat, metas) API — the shape local_sgd.py's fragment sync (and its
# tests) use; kept as thin wrappers over the plan machinery so there is one
# grouping/packing implementation.


def make_buckets(arrays: List[Any], cap_bytes: int) -> List[tuple]:
    """Pack arrays into flat same-dtype buckets of at most ``cap_bytes``.

    Returns ``[(flat_buffer, metas), ...]`` with ``metas = [(arr_index,
    offset, size, shape), ...]``.
    """
    plan = build_plan(arrays, cap_bytes)
    flats, _pooled = pack(arrays, plan)
    return list(zip(flats, plan.metas))


def pack_group(arrays: List[Any], idxs: List[int]) -> tuple:
    """Pack one explicit index group into ``(flat, metas)``."""
    import jax

    metas: List[Meta] = []
    offset = 0
    for i in idxs:
        a = arrays[i]
        metas.append((i, offset, _leaf_size(a), _leaf_shape(a)))
        offset += _leaf_size(a)
    if all(isinstance(arrays[i], jax.Array) for i in idxs):
        import jax.numpy as jnp

        flat = jnp.concatenate([arrays[i].reshape(-1) for i in idxs])
    else:
        flat = np.empty(offset, dtype=_leaf_dtype(arrays[idxs[0]]))
        for (i, off, size, _shape) in metas:
            flat[off : off + size] = np.asarray(arrays[i]).reshape(-1)
    return flat, metas


def unpack_buckets(
    buckets_out: List[Any], bucket_metas: List[List[tuple]], n: int
) -> List[Any]:
    """Inverse of :func:`make_buckets` over reduced flats."""
    import jax

    out: List[Optional[Any]] = [None] * n
    for flat, metas in zip(buckets_out, bucket_metas):
        if not isinstance(flat, jax.Array):
            flat = np.asarray(flat)
        for (i, off, size, shape) in metas:
            out[i] = flat[off : off + size].reshape(shape)
    assert all(o is not None for o in out)
    return out  # type: ignore[return-value]
