"""Python bindings for the native C++ control plane.

Role-equivalent of the reference's pyo3 extension ``torchft._torchft``
(reference: src/lib.rs:80-761, torchft/_torchft.pyi, torchft/coordination.py):
``LighthouseServer``/``LighthouseClient``, ``ManagerServer``/``ManagerClient``,
``QuorumResult``, plus the rendezvous ``KvStoreServer``/``KvClient`` (the
TPU-native replacement for torch's TCPStore). The native side is C++
(``native/`` -> ``torchft_tpu/_native/libtorchft_tpu.so``) speaking
length-framed JSON over TCP; ctypes releases the GIL around every blocking
RPC, matching the reference's ``py.allow_threads`` behavior.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
from dataclasses import dataclass, field
from datetime import timedelta
from typing import Callable, Dict, List, Optional, Tuple

from .retry import RetryPolicy, retry_call

__all__ = [
    "QuorumMember",
    "Quorum",
    "QuorumResult",
    "FallbackPeer",
    "LighthouseServer",
    "LighthouseClient",
    "AggregatorServer",
    "ManagerServer",
    "ManagerClient",
    "KvStoreServer",
    "KvClient",
]

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "_native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libtorchft_tpu.so")

# /metrics per-replica series cap (see LighthouseServer / docs/operations.md).
METRICS_PER_REPLICA_LIMIT_ENV = "TORCHFT_METRICS_PER_REPLICA_LIMIT"

# status codes from native/capi.cc
_OK, _TIMEOUT, _ERROR, _NOT_FOUND, _INVALID, _UNAVAILABLE = range(6)


def ensure_native_built() -> str:
    """Build the native library if missing (requires g++ + make).

    Serialized across processes with a file lock so a multi-process launch on
    a fresh checkout doesn't race the build.
    """
    if not os.path.exists(_SO_PATH):
        native_src = os.path.join(os.path.dirname(_NATIVE_DIR), "..", "native")
        native_src = os.path.abspath(native_src)
        if not os.path.isdir(native_src):
            raise RuntimeError(
                f"native library missing at {_SO_PATH} and no source tree found"
            )
        import fcntl

        os.makedirs(_NATIVE_DIR, exist_ok=True)
        lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            try:
                if not os.path.exists(_SO_PATH):  # re-check under the lock
                    subprocess.run(["make", "-C", native_src, "-j"], check=True)
            finally:
                fcntl.flock(lock, fcntl.LOCK_UN)
    return _SO_PATH


_lib: Optional[ctypes.CDLL] = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(ensure_native_built())
        lib.tft_free.argtypes = [ctypes.c_char_p]
        lib.tft_free.restype = None
        lib.tft_lighthouse_new.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tft_lighthouse_new_v2.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tft_lighthouse_address.argtypes = [ctypes.c_void_p]
        lib.tft_lighthouse_address.restype = ctypes.c_void_p
        lib.tft_lighthouse_port.argtypes = [ctypes.c_void_p]
        lib.tft_lighthouse_shutdown.argtypes = [ctypes.c_void_p]
        lib.tft_lighthouse_free.argtypes = [ctypes.c_void_p]
        # policy plane: in-process control surface (NOT wire RPCs)
        lib.tft_lighthouse_set_policy.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tft_lighthouse_policy.argtypes = [ctypes.c_void_p]
        lib.tft_lighthouse_policy.restype = ctypes.c_void_p
        lib.tft_lighthouse_drain_events.argtypes = [ctypes.c_void_p]
        lib.tft_lighthouse_drain_events.restype = ctypes.c_void_p
        lib.tft_lighthouse_retune_health.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tft_aggregator_new.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tft_aggregator_address.argtypes = [ctypes.c_void_p]
        lib.tft_aggregator_address.restype = ctypes.c_void_p
        lib.tft_aggregator_status.argtypes = [ctypes.c_void_p]
        lib.tft_aggregator_status.restype = ctypes.c_void_p
        lib.tft_aggregator_port.argtypes = [ctypes.c_void_p]
        lib.tft_aggregator_shutdown.argtypes = [ctypes.c_void_p]
        lib.tft_aggregator_free.argtypes = [ctypes.c_void_p]
        lib.tft_manager_new.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tft_manager_control_status.argtypes = [ctypes.c_void_p]
        lib.tft_manager_control_status.restype = ctypes.c_void_p
        lib.tft_manager_address.argtypes = [ctypes.c_void_p]
        lib.tft_manager_address.restype = ctypes.c_void_p
        lib.tft_manager_publish_telemetry.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tft_manager_health.argtypes = [ctypes.c_void_p]
        lib.tft_manager_health.restype = ctypes.c_void_p
        lib.tft_manager_policy.argtypes = [ctypes.c_void_p]
        lib.tft_manager_policy.restype = ctypes.c_void_p
        lib.tft_manager_clock_skew.argtypes = [ctypes.c_void_p]
        lib.tft_manager_clock_skew.restype = ctypes.c_void_p
        lib.tft_manager_port.argtypes = [ctypes.c_void_p]
        lib.tft_manager_shutdown.argtypes = [ctypes.c_void_p]
        lib.tft_manager_free.argtypes = [ctypes.c_void_p]
        lib.tft_client_new.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tft_client_free.argtypes = [ctypes.c_void_p]
        lib.tft_client_call.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tft_kvstore_new.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tft_kvstore_port.argtypes = [ctypes.c_void_p]
        lib.tft_kvstore_shutdown.argtypes = [ctypes.c_void_p]
        lib.tft_kvstore_free.argtypes = [ctypes.c_void_p]
        lib.tft_quorum_compute.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tft_compute_quorum_results.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tft_health_scores.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tft_health_replay.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tft_history_replay.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_char_p),
        ]
        _lib = lib
    return _lib


def _take_str(lib: ctypes.CDLL, ptr: "ctypes.c_char_p | int | None") -> str:
    if not ptr:
        return ""
    try:
        raw = ctypes.cast(ptr, ctypes.c_char_p).value or b""
        return raw.decode("utf-8", errors="replace")
    finally:
        lib.tft_free(ctypes.cast(ptr, ctypes.c_char_p))


def _raise_for_status(status: int, err: str, what: str) -> None:
    if status == _OK:
        return
    msg = f"{what}: {err}" if err else what
    if status == _TIMEOUT:
        raise TimeoutError(msg)
    if status == _NOT_FOUND:
        raise LookupError(msg)
    if status == _INVALID:
        raise ValueError(msg)
    raise RuntimeError(msg)


def _ms(timeout: "float | timedelta") -> int:
    if isinstance(timeout, timedelta):
        return int(timeout.total_seconds() * 1000)
    return int(timeout * 1000)


# --------------------------------------------------------------------- types
@dataclass
class QuorumMember:
    """Mirror of the wire QuorumMember (reference: proto/torchft.proto:37-47)."""

    replica_id: str
    address: str = ""
    store_address: str = ""
    step: int = 0
    world_size: int = 1
    shrink_only: bool = False
    commit_failures: int = 0
    data: str = ""

    @staticmethod
    def _from_json(d: dict) -> "QuorumMember":
        return QuorumMember(
            replica_id=d["replica_id"],
            address=d.get("address", ""),
            store_address=d.get("store_address", ""),
            step=d.get("step", 0),
            world_size=d.get("world_size", 1),
            shrink_only=d.get("shrink_only", False),
            commit_failures=d.get("commit_failures", 0),
            data=d.get("data", ""),
        )

    def _to_json(self) -> dict:
        return {
            "replica_id": self.replica_id,
            "address": self.address,
            "store_address": self.store_address,
            "step": self.step,
            "world_size": self.world_size,
            "shrink_only": self.shrink_only,
            "commit_failures": self.commit_failures,
            "data": self.data,
        }


@dataclass
class Quorum:
    quorum_id: int
    participants: List[QuorumMember]
    created_ms: int = 0

    @staticmethod
    def _from_json(d: dict) -> "Quorum":
        return Quorum(
            quorum_id=d["quorum_id"],
            participants=[QuorumMember._from_json(p) for p in d["participants"]],
            created_ms=d.get("created_ms", 0),
        )


@dataclass
class FallbackPeer:
    """An up-to-date peer a healing replica can fail over to if its assigned
    recovery source dies mid-transfer."""

    replica_rank: int
    address: str  # manager RPC address (host:port)

    @staticmethod
    def _from_json(d: dict) -> "FallbackPeer":
        return FallbackPeer(
            replica_rank=d.get("replica_rank", 0), address=d.get("address", "")
        )


@dataclass
class QuorumResult:
    """Per-rank manager quorum response (reference: proto ManagerQuorumResponse
    + src/lib.rs:284-319)."""

    quorum_id: int
    replica_rank: int
    replica_world_size: int
    recover_src_manager_address: str
    recover_src_replica_rank: Optional[int]
    recover_dst_replica_ranks: List[int]
    store_address: str
    max_step: int
    max_replica_rank: Optional[int]
    max_world_size: int
    heal: bool
    commit_failures: int = 0
    replica_ids: List[str] = field(default_factory=list)
    # remaining max_step peers in round-robin order after the assigned
    # source; empty when not healing or from a pre-fallback native build
    recover_src_fallbacks: List[FallbackPeer] = field(default_factory=list)

    @staticmethod
    def _from_json(d: dict) -> "QuorumResult":
        return QuorumResult(
            quorum_id=d["quorum_id"],
            replica_rank=d["replica_rank"],
            replica_world_size=d["replica_world_size"],
            recover_src_manager_address=d.get("recover_src_manager_address", ""),
            recover_src_replica_rank=d.get("recover_src_replica_rank"),
            recover_dst_replica_ranks=list(d.get("recover_dst_replica_ranks", [])),
            store_address=d.get("store_address", ""),
            max_step=d.get("max_step", 0),
            max_replica_rank=d.get("max_replica_rank"),
            max_world_size=d.get("max_world_size", 0),
            heal=d.get("heal", False),
            commit_failures=d.get("commit_failures", 0),
            replica_ids=list(d.get("replica_ids", [])),
            recover_src_fallbacks=[
                FallbackPeer._from_json(f)
                for f in d.get("recover_src_fallbacks", [])
            ],
        )


# ------------------------------------------------------------------- servers
class LighthouseServer:
    """In-process lighthouse quorum server (native C++).

    Reference equivalent: ``LighthouseServer`` in src/lib.rs:609-671 backed by
    src/lighthouse.rs. Also serves the HTML dashboard + ``/status`` JSON +
    ``POST /replica/{id}/kill`` on the same port.
    """

    def __init__(
        self,
        bind: str = "0.0.0.0:0",
        min_replicas: int = 1,
        join_timeout_ms: int = 60000,
        quorum_tick_ms: int = 100,
        heartbeat_timeout_ms: int = 5000,
        health: "Optional[dict]" = None,
        history_path: str = "",
        metrics_per_replica_limit: "Optional[int]" = None,
        serve_registry: bool = False,
        serve_drain_on: "Optional[str]" = None,
        redundancy_directory: bool = False,
        policy: "Optional[str]" = None,
    ) -> None:
        """``health`` configures the healthwatch ledger (HealthOpts fields,
        see torchft_tpu/healthwatch.py); None reads ``TORCHFT_HEALTH_*``
        from the environment (default: observe mode). ``history_path``
        enables the recorded-history store: append-only JSONL of quorum
        transitions / heals / health events / telemetry snapshots, readable
        via :func:`history_replay` (empty = disabled).
        ``metrics_per_replica_limit`` caps per-replica /metrics series (the
        tail collapses into min/median/max aggregates); None reads
        ``TORCHFT_METRICS_PER_REPLICA_LIMIT`` (default 64).
        ``serve_registry=True`` co-hosts a serving-plane SnapshotRegistry
        that polls this lighthouse's /health summary to drain unhealthy
        sources (``serve_drain_on``: "warn"/"eject", None reads
        ``TORCHFT_SERVE_DRAIN_ON``); see docs/serving.md.
        ``redundancy_directory=True`` co-hosts a redundancy-plane
        ShardDirectory that tracks erasure-coded shard placements, polls
        this lighthouse's /health ledger for owner deaths, and promotes
        hot spares into the next quorum (docs/operations.md).
        ``policy`` attaches the adaptive policy engine: ``"builtin"`` or a
        PolicySpec JSON path (None reads ``TORCHFT_POLICY_SPEC`` when
        ``TORCHFT_POLICY`` != off). The engine folds this lighthouse's
        live event ring into fleet signals every
        ``TORCHFT_POLICY_INTERVAL_S`` and publishes versioned knob-
        override frames on existing heartbeat/agg_tick replies; see
        docs/operations.md#adaptive-policies."""
        from torchft_tpu import knobs

        lib = _load()
        policy_mode = knobs.env_str("TORCHFT_POLICY", "off").strip() or "off"
        if policy is None and policy_mode != "off":
            policy = knobs.env_str("TORCHFT_POLICY_SPEC", "builtin") or "builtin"
        policy_ring = (
            knobs.env_int("TORCHFT_POLICY_RING", 4096)
            if policy is not None and policy_mode != "off"
            else 0
        )
        if health is None:
            from torchft_tpu.healthwatch import HealthConfig

            health = HealthConfig.from_env().to_json()
        if metrics_per_replica_limit is None:
            metrics_per_replica_limit = int(
                os.environ.get(METRICS_PER_REPLICA_LIMIT_ENV, "") or 64
            )
        handle = ctypes.c_void_p()
        err = ctypes.c_char_p()
        opts = {
            "bind": bind,
            "min_replicas": min_replicas,
            "join_timeout_ms": join_timeout_ms,
            "quorum_tick_ms": quorum_tick_ms,
            "heartbeat_timeout_ms": heartbeat_timeout_ms,
            "health": health,
            "history_path": history_path,
            "policy_ring": policy_ring,
            "metrics_per_replica_limit": metrics_per_replica_limit,
        }
        status = lib.tft_lighthouse_new_v2(
            json.dumps(opts).encode(), ctypes.byref(handle), ctypes.byref(err)
        )
        _raise_for_status(status, _take_str(lib, err), "lighthouse start failed")
        self._lib = lib
        self._handle = handle
        self.serve_registry = None
        if serve_registry:
            # lazy import: the serving plane is optional and serving.py
            # imports back into this module for its health poll client
            from torchft_tpu.serving import SERVE_DRAIN_ON_ENV, SnapshotRegistry

            drain_on = (
                serve_drain_on
                if serve_drain_on is not None
                else os.environ.get(SERVE_DRAIN_ON_ENV, "warn").strip() or "warn"
            )
            self.serve_registry = SnapshotRegistry(
                lighthouse_addr=self.address(), drain_on=drain_on
            )
        self.redundancy_directory = None
        if redundancy_directory:
            # lazy import, same reason as the serving registry above:
            # redundancy.py imports LighthouseClient back from here for
            # the directory's health poll
            from torchft_tpu.redundancy import ShardDirectory

            self.redundancy_directory = ShardDirectory(
                lighthouse_addr=self.address()
            )
        self.policy_controller = None
        self.policy_mode = policy_mode
        self._policy_thread = None
        self._policy_stop = None
        if policy is not None and policy_mode != "off":
            self._attach_policy(policy, policy_mode)

    def _attach_policy(self, policy: str, mode: str) -> None:
        """Python-side lazy attach (same pattern as serve_registry /
        redundancy_directory): a PolicyController polling the native
        handle's event ring on a daemon thread."""
        import threading

        from torchft_tpu import knobs
        from torchft_tpu.policy import (
            PolicyController,
            PolicyEngine,
            PolicySpec,
        )

        spec = PolicySpec.load(policy)
        engine = PolicyEngine(
            spec,
            mode=mode,
            window_s=knobs.env_float("TORCHFT_POLICY_WINDOW_S", 300.0),
        )
        self.policy_controller = PolicyController(
            engine,
            drain_fn=self._policy_drain,
            set_policy_fn=self.set_policy,
            retune_health_fn=self.retune_health,
        )
        interval_s = max(knobs.env_float("TORCHFT_POLICY_INTERVAL_S", 5.0), 0.05)
        stop = threading.Event()

        def _loop() -> None:
            while not stop.wait(interval_s):
                try:
                    self.policy_controller.step()
                except Exception:  # noqa: BLE001 — the policy plane must
                    pass  # never take down the quorum coordinator

        self._policy_stop = stop
        self._policy_thread = threading.Thread(
            target=_loop, name="torchft-policy", daemon=True
        )
        self._policy_thread.start()

    def _policy_drain(self) -> "List[dict]":
        raw = _take_str(
            self._lib, self._lib.tft_lighthouse_drain_events(self._handle)
        )
        return json.loads(raw or "[]")

    def set_policy(self, frame: dict) -> None:
        """Publish a policy frame onto heartbeat/agg_tick replies (``{}``
        clears it — the kill switch)."""
        err = ctypes.c_char_p()
        status = self._lib.tft_lighthouse_set_policy(
            self._handle, json.dumps(frame).encode(), ctypes.byref(err)
        )
        _raise_for_status(
            status, _take_str(self._lib, err), "set_policy failed"
        )

    def policy(self) -> dict:
        """The currently published policy frame (``{}`` when none)."""
        return json.loads(
            _take_str(self._lib, self._lib.tft_lighthouse_policy(self._handle))
            or "{}"
        )

    def retune_health(self, partial: dict) -> dict:
        """Live-merge partial HealthOpts over the running ledger (policy
        enforce mode tightening/widening eject thresholds). Returns the
        resulting opts."""
        out = ctypes.c_char_p()
        err = ctypes.c_char_p()
        status = self._lib.tft_lighthouse_retune_health(
            self._handle, json.dumps(partial).encode(),
            ctypes.byref(out), ctypes.byref(err),
        )
        out_s = _take_str(self._lib, out)
        _raise_for_status(
            status, _take_str(self._lib, err), "retune_health failed"
        )
        return json.loads(out_s or "{}")

    def address(self) -> str:
        return _take_str(self._lib, self._lib.tft_lighthouse_address(self._handle))

    @property
    def port(self) -> int:
        return self._lib.tft_lighthouse_port(self._handle)

    def serve_registry_url(self) -> "Optional[str]":
        return self.serve_registry.url if self.serve_registry is not None else None

    def redundancy_directory_url(self) -> "Optional[str]":
        return (
            self.redundancy_directory.url
            if self.redundancy_directory is not None
            else None
        )

    def shutdown(self) -> None:
        if self._policy_stop is not None:
            self._policy_stop.set()
            if self._policy_thread is not None:
                self._policy_thread.join(timeout=5.0)
            self._policy_stop = None
            self._policy_thread = None
            self.policy_controller = None
        if self.serve_registry is not None:
            self.serve_registry.shutdown()
            self.serve_registry = None
        if self.redundancy_directory is not None:
            self.redundancy_directory.shutdown()
            self.redundancy_directory = None
        if self._handle:
            self._lib.tft_lighthouse_shutdown(self._handle)

    def __del__(self) -> None:
        try:
            if getattr(self, "_handle", None):
                self._lib.tft_lighthouse_free(self._handle)
                self._handle = None
        except Exception:
            pass


class AggregatorServer:
    """Pod-level lighthouse aggregator (native C++, ``native/aggregator.cc``).

    Fronts a pod of replica-group managers: speaks the lighthouse wire
    protocol downstream (``heartbeat`` / ``quorum`` / ``GET /status``) so a
    manager points at it via ``TORCHFT_LIGHTHOUSE_AGGREGATOR`` with zero API
    changes, and batches the pod into one delta-encoded ``agg_tick`` RPC per
    tick upstream to the root lighthouse.
    """

    def __init__(
        self,
        root_addr: str,
        bind: str = "0.0.0.0:0",
        agg_id: str = "",
        tick_ms: int = 100,
        heartbeat_timeout_ms: int = 5000,
        connect_timeout: "float | timedelta" = 10.0,
    ) -> None:
        lib = _load()
        handle = ctypes.c_void_p()
        err = ctypes.c_char_p()
        opts = {
            "bind": bind,
            "root_addr": root_addr,
            "agg_id": agg_id,
            "tick_ms": tick_ms,
            "heartbeat_timeout_ms": heartbeat_timeout_ms,
            "connect_timeout_ms": _ms(connect_timeout),
        }
        status = lib.tft_aggregator_new(
            json.dumps(opts).encode(), ctypes.byref(handle), ctypes.byref(err)
        )
        _raise_for_status(status, _take_str(lib, err), "aggregator start failed")
        self._lib = lib
        self._handle = handle

    def address(self) -> str:
        return _take_str(self._lib, self._lib.tft_aggregator_address(self._handle))

    def status(self) -> dict:
        """Pod + upstream view: pod_size/pod_live, joiners_pending,
        ticks_ok/ticks_failed, upstream_bytes, last_tick_ok, last_error."""
        return json.loads(
            _take_str(self._lib, self._lib.tft_aggregator_status(self._handle))
            or "{}"
        )

    @property
    def port(self) -> int:
        return self._lib.tft_aggregator_port(self._handle)

    def shutdown(self) -> None:
        if self._handle:
            self._lib.tft_aggregator_shutdown(self._handle)

    def __del__(self) -> None:
        try:
            if getattr(self, "_handle", None):
                self._lib.tft_aggregator_free(self._handle)
                self._handle = None
        except Exception:
            pass


class ManagerServer:
    """Per-replica-group manager server (native C++).

    Reference equivalent: ``ManagerServer`` in src/lib.rs:80-144 backed by
    src/manager.rs.
    """

    def __init__(
        self,
        replica_id: str,
        lighthouse_addr: str,
        hostname: str = "",
        bind: str = "0.0.0.0:0",
        store_addr: str = "",
        world_size: int = 1,
        heartbeat_interval: "float | timedelta" = 0.1,
        connect_timeout: "float | timedelta" = 10.0,
        quorum_retries: int = 0,
        aggregator_addr: str = "",
    ) -> None:
        """``aggregator_addr`` points control RPCs at a pod aggregator
        (:class:`AggregatorServer`); empty = flat fleet, direct to the
        lighthouse. The manager fails over to direct-to-root on its own if
        the aggregator dies and re-points when the root names a
        replacement."""
        lib = _load()
        handle = ctypes.c_void_p()
        err = ctypes.c_char_p()
        opts = {
            "replica_id": replica_id,
            "lighthouse_addr": lighthouse_addr,
            "hostname": hostname,
            "bind": bind,
            "store_addr": store_addr,
            "world_size": world_size,
            "heartbeat_interval_ms": _ms(heartbeat_interval),
            "connect_timeout_ms": _ms(connect_timeout),
            "quorum_retries": quorum_retries,
            "aggregator_addr": aggregator_addr,
        }
        status = lib.tft_manager_new(
            json.dumps(opts).encode(), ctypes.byref(handle), ctypes.byref(err)
        )
        _raise_for_status(status, _take_str(lib, err), "manager start failed")
        self._lib = lib
        self._handle = handle

    def address(self) -> str:
        return _take_str(self._lib, self._lib.tft_manager_address(self._handle))

    def publish_telemetry(self, telemetry: dict) -> None:
        """Set the per-step telemetry payload the background heartbeat
        thread piggybacks on every beat (healthwatch plane). Keys the
        lighthouse ledger reads: ``step``, ``step_s``, ``wire_s``; anything
        else rides along for the /health dashboard."""
        err = ctypes.c_char_p()
        status = self._lib.tft_manager_publish_telemetry(
            self._handle, json.dumps(telemetry).encode(), ctypes.byref(err)
        )
        _raise_for_status(
            status, _take_str(self._lib, err), "publish_telemetry failed"
        )

    def health(self) -> dict:
        """This replica's health summary from the last heartbeat response
        (state / state_code / score / ejections / readmissions); ``{}``
        until the first beat round-trips."""
        return json.loads(
            _take_str(self._lib, self._lib.tft_manager_health(self._handle))
            or "{}"
        )

    def policy(self) -> dict:
        """The latest adaptive-policy frame carried on a heartbeat reply
        (directly from the root, or fanned out by the pod aggregator):
        ``{"policy_seq", "mode", "knob_overrides", "active_rules"}``.
        ``{}`` until a frame arrives. The Manager polls this at its
        quorum safe point; the beat loop never interprets it."""
        return json.loads(
            _take_str(self._lib, self._lib.tft_manager_policy(self._handle))
            or "{}"
        )

    def clock_skew(self) -> dict:
        """Clock-skew estimate vs the lighthouse from heartbeat round-trips,
        replica-minus-lighthouse: positive when this host's clock runs
        ahead (``skew_ms``/``rtt_ms`` from the minimum-RTT beat, plus
        ``last_skew_ms``/``last_rtt_ms``/``samples``). ``samples`` is 0
        until the first beat round-trips; the tracing plane stamps
        ``skew_ms`` into every span export so the trace merger can place N
        replicas on one corrected timeline."""
        return json.loads(
            _take_str(
                self._lib, self._lib.tft_manager_clock_skew(self._handle)
            )
            or "{}"
        )

    def control_status(self) -> dict:
        """Two-level control plane view: ``aggregator_addr`` /
        ``via_aggregator`` / ``direct_mode`` / ``failovers`` — which
        upstream the heartbeat/quorum RPCs currently use."""
        return json.loads(
            _take_str(
                self._lib, self._lib.tft_manager_control_status(self._handle)
            )
            or "{}"
        )

    @property
    def port(self) -> int:
        return self._lib.tft_manager_port(self._handle)

    def shutdown(self) -> None:
        if self._handle:
            self._lib.tft_manager_shutdown(self._handle)

    def __del__(self) -> None:
        try:
            if getattr(self, "_handle", None):
                self._lib.tft_manager_free(self._handle)
                self._handle = None
        except Exception:
            pass


class KvStoreServer:
    """Rendezvous key-value store server (native C++; TCPStore equivalent)."""

    def __init__(self, bind: str = "0.0.0.0:0") -> None:
        lib = _load()
        handle = ctypes.c_void_p()
        err = ctypes.c_char_p()
        status = lib.tft_kvstore_new(
            bind.encode(), ctypes.byref(handle), ctypes.byref(err)
        )
        _raise_for_status(status, _take_str(lib, err), "kvstore start failed")
        self._lib = lib
        self._handle = handle

    @property
    def port(self) -> int:
        return self._lib.tft_kvstore_port(self._handle)

    def address(self) -> str:
        import socket

        return f"{socket.gethostname()}:{self.port}"

    def shutdown(self) -> None:
        if self._handle:
            self._lib.tft_kvstore_shutdown(self._handle)

    def __del__(self) -> None:
        try:
            if getattr(self, "_handle", None):
                self._lib.tft_kvstore_free(self._handle)
                self._handle = None
        except Exception:
            pass


# ------------------------------------------------------------------- clients
# Test-only fault injection: called before every RPC attempt with
# (method, addr); may sleep (to model a slow link) and/or return an exception
# to raise in place of the real call (to model a flaky/partitioned server).
# Lets tests exercise the retry paths deterministically without real outages.
_rpc_fault_hook: Optional[Callable[[str, str], Optional[Exception]]] = None


def set_rpc_fault_hook(
    hook: Optional[Callable[[str, str], Optional[Exception]]],
) -> None:
    """Install (or clear, with None) the process-wide RPC fault hook."""
    global _rpc_fault_hook
    _rpc_fault_hook = hook


# Exceptions worth retrying: connection-class failures (_UNAVAILABLE/_ERROR
# map to RuntimeError, stalls to TimeoutError). _NOT_FOUND/_INVALID are
# semantic errors — retrying cannot change the answer.
_RETRYABLE_RPC_ERRORS = (TimeoutError, RuntimeError, ConnectionError)

# Connection-loss classes retry with FULL jitter (uniform [0, ceiling]): a
# restarted lighthouse drops every replica at the same instant, and bounded
# jitter would wake the whole herd inside the top half of each backoff
# window (retry.RetryPolicy.backoff_s). Timeouts keep bounded jitter — they
# are not herd-synchronized and bounded jitter preserves deadline pacing.
_FULL_JITTER_RPC_ERRORS = (ConnectionError, RuntimeError)


def _seconds(timeout: "float | timedelta") -> float:
    if isinstance(timeout, timedelta):
        return timeout.total_seconds()
    return float(timeout)


class _RawClient:
    """Generic framed-JSON RPC client over the native transport.

    Every call runs under the shared jittered-backoff retry policy
    (``TORCHFT_RETRY_*`` env knobs; ``TORCHFT_RETRY_MAX_ATTEMPTS=1``
    disables) with the caller's timeout as the hard deadline budget — the
    native ``RpcClient`` re-dials a stale cached connection per attempt, so
    a server blip shorter than the budget degrades to a slower call rather
    than an errored one. On exhaustion the *last underlying* exception is
    re-raised so callers keep their exact pre-retry exception taxonomy.
    """

    def __init__(
        self,
        addr: str,
        connect_timeout: "float | timedelta" = 10.0,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self._lib = _load()
        handle = ctypes.c_void_p()
        err = ctypes.c_char_p()
        status = self._lib.tft_client_new(
            addr.encode(), _ms(connect_timeout), ctypes.byref(handle),
            ctypes.byref(err),
        )
        _raise_for_status(status, _take_str(self._lib, err), "client create failed")
        self._handle = handle
        self.addr = addr
        self._retry_policy = (
            retry_policy if retry_policy is not None else RetryPolicy.from_env()
        )
        # observer: (method, attempt, prior_exception) on every retry attempt
        self.on_retry: Optional[Callable[[str, int, BaseException], None]] = None

    def call(
        self,
        method: str,
        params: dict,
        timeout: "float | timedelta",
        retry: bool = True,
    ) -> dict:
        return self.call_raw(method, json.dumps(params).encode(), timeout, retry)

    def _call_once(
        self, method: str, params_json: bytes, timeout: "float | timedelta"
    ) -> dict:
        hook = _rpc_fault_hook
        if hook is not None:
            injected = hook(method, self.addr)
            if injected is not None:
                raise injected
        result = ctypes.c_char_p()
        err = ctypes.c_char_p()
        status = self._lib.tft_client_call(
            self._handle, method.encode(), params_json,
            _ms(timeout), ctypes.byref(result), ctypes.byref(err),
        )
        err_s = _take_str(self._lib, err)
        result_s = _take_str(self._lib, result)
        _raise_for_status(status, err_s, f"{method} to {self.addr} failed")
        return json.loads(result_s) if result_s else {}

    def call_raw(
        self,
        method: str,
        params_json: bytes,
        timeout: "float | timedelta",
        retry: bool = True,
    ) -> dict:
        """Like :meth:`call` but takes the params frame pre-encoded —
        per-step callers (the commit vote) build their frame once and
        splice in what changes, skipping json.dumps on the hot path.

        ``retry=False`` opts a call out of the retry policy — required for
        non-idempotent RPCs (``add``) and fire-and-forget ones (``kill``)."""
        policy = self._retry_policy
        if not retry or not policy.enabled:
            return self._call_once(method, params_json, timeout)

        def _on_attempt(attempt: int, prior: Optional[BaseException]) -> None:
            if attempt > 1 and prior is not None and self.on_retry is not None:
                self.on_retry(method, attempt, prior)

        from .retry import RetryBudgetExhausted

        try:
            return retry_call(
                lambda remaining: self._call_once(method, params_json, remaining),
                policy,
                timeout=_seconds(timeout),
                retryable=_RETRYABLE_RPC_ERRORS,
                full_jitter_on=_FULL_JITTER_RPC_ERRORS,
                on_attempt=_on_attempt,
            )
        except RetryBudgetExhausted as e:
            # preserve the pre-retry exception taxonomy for callers
            # (RuntimeError stays RuntimeError, TimeoutError TimeoutError)
            assert e.last_exception is not None
            raise e.last_exception from e

    def __del__(self) -> None:
        try:
            if getattr(self, "_handle", None):
                self._lib.tft_client_free(self._handle)
                self._handle = None
        except Exception:
            pass


class LighthouseClient:
    """Client for the lighthouse service (reference: src/lib.rs:486-594)."""

    def __init__(
        self,
        addr: str,
        connect_timeout: "float | timedelta" = 10.0,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self._client = _RawClient(addr, connect_timeout, retry_policy)

    def set_retry_observer(
        self, fn: Optional[Callable[[str, int, BaseException], None]]
    ) -> None:
        """Observer called as ``fn(method, attempt, prior_exc)`` on each RPC
        retry attempt (never on the first attempt)."""
        self._client.on_retry = fn

    def quorum(
        self,
        replica_id: str,
        timeout: "float | timedelta",
        address: str = "",
        store_address: str = "",
        step: int = 0,
        world_size: int = 1,
        shrink_only: bool = False,
        data: Optional[Dict] = None,
        commit_failures: int = 0,
    ) -> Quorum:
        member = QuorumMember(
            replica_id=replica_id,
            address=address,
            store_address=store_address,
            step=step,
            world_size=world_size,
            shrink_only=shrink_only,
            commit_failures=commit_failures,
            data=json.dumps(data) if data is not None else "",
        )
        resp = self._client.call("quorum", {"requester": member._to_json()}, timeout)
        return Quorum._from_json(resp["quorum"])

    def heartbeat(
        self,
        replica_id: str,
        timeout: "float | timedelta" = 5.0,
        telemetry: Optional[dict] = None,
    ) -> dict:
        """Beat once; optionally carries a healthwatch telemetry payload.
        Returns the lighthouse's response (``health`` key: this replica's
        health summary)."""
        params: Dict = {"replica_id": replica_id}
        if telemetry is not None:
            params["telemetry"] = telemetry
        return self._client.call("heartbeat", params, timeout)

    def status(self, timeout: "float | timedelta" = 5.0) -> dict:
        return self._client.call("status", {}, timeout)

    def health(self, timeout: "float | timedelta" = 5.0) -> dict:
        """Full healthwatch ledger dump (same payload as GET /health)."""
        return self._client.call("health", {}, timeout)


class ManagerClient:
    """Client for a replica group's manager service (reference: src/lib.rs:153-282)."""

    def __init__(
        self,
        addr: str,
        connect_timeout: "float | timedelta" = 10.0,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self._client = _RawClient(addr, connect_timeout, retry_policy)
        # pre-built vote frames keyed by (group_rank, vote): everything but
        # the step number is invariant across a training run, so the
        # per-step should_commit only splices the step into a cached prefix
        # instead of re-serializing the params dict (see should_commit)
        self._vote_frames: Dict[Tuple[int, bool], bytes] = {}

    def set_retry_observer(
        self, fn: Optional[Callable[[str, int, BaseException], None]]
    ) -> None:
        """Observer called as ``fn(method, attempt, prior_exc)`` on each RPC
        retry attempt (never on the first attempt)."""
        self._client.on_retry = fn

    def _quorum(
        self,
        group_rank: int,
        step: int,
        checkpoint_metadata: str,
        shrink_only: bool,
        timeout: "float | timedelta",
        init_sync: bool = True,
        commit_failures: int = 0,
    ) -> QuorumResult:
        resp = self._client.call(
            "quorum",
            {
                "group_rank": group_rank,
                "step": step,
                "checkpoint_metadata": checkpoint_metadata,
                "shrink_only": shrink_only,
                "init_sync": init_sync,
                "commit_failures": commit_failures,
            },
            timeout,
        )
        return QuorumResult._from_json(resp)

    def _checkpoint_metadata(self, rank: int, timeout: "float | timedelta") -> str:
        resp = self._client.call("checkpoint_metadata", {"rank": rank}, timeout)
        return resp["checkpoint_metadata"]

    def should_commit(
        self,
        group_rank: int,
        step: int,
        should_commit: bool,
        timeout: "float | timedelta",
    ) -> bool:
        key = (group_rank, should_commit)
        prefix = self._vote_frames.get(key)
        if prefix is None:
            # '{"group_rank": N, "should_commit": B}' -> strip the closing
            # brace, leave a slot for the step: '...,"step":'
            head = json.dumps(
                {"group_rank": group_rank, "should_commit": should_commit}
            ).encode()
            prefix = head[:-1] + b', "step": '
            self._vote_frames[key] = prefix
        resp = self._client.call_raw(
            "should_commit", prefix + str(step).encode() + b"}", timeout
        )
        return resp["should_commit"]

    def kill(self, msg: str = "", timeout: "float | timedelta" = 5.0) -> None:
        try:
            # fire-and-forget: never retried (the target exits mid-reply)
            self._client.call("kill", {"msg": msg}, timeout, retry=False)
        except (RuntimeError, TimeoutError):
            pass  # the target exits without replying


class KvClient:
    """Client for the rendezvous KV store.

    ``set`` values are arbitrary bytes ("b64:"-prefixed base64 on the wire);
    ``add`` counters are stored by the server as plain decimal text — ``get``
    handles both transparently.
    """

    def __init__(
        self,
        addr: str,
        connect_timeout: "float | timedelta" = 10.0,
        retry_policy: Optional[RetryPolicy] = None,
    ):
        self._client = _RawClient(addr, connect_timeout, retry_policy)

    def set_retry_observer(
        self, fn: Optional[Callable[[str, int, BaseException], None]]
    ) -> None:
        self._client.on_retry = fn

    def set(self, key: str, value: "bytes | str", timeout: "float | timedelta" = 10.0) -> None:
        import base64

        if isinstance(value, str):
            value = value.encode()
        self._client.call(
            "set",
            {"key": key, "value": "b64:" + base64.b64encode(value).decode()},
            timeout,
        )

    def get(
        self, key: str, timeout: "float | timedelta" = 10.0, wait: bool = True
    ) -> bytes:
        import base64

        resp = self._client.call("get", {"key": key, "wait": wait}, timeout)
        value = resp["value"]
        if value.startswith("b64:"):
            return base64.b64decode(value[4:])
        return value.encode()  # add() counter or other plain-text value

    def add(self, key: str, amount: int, timeout: "float | timedelta" = 10.0) -> int:
        # non-idempotent: a retry after a lost reply would double-count
        return self._client.call(
            "add", {"key": key, "amount": amount}, timeout, retry=False
        )["value"]

    def check(self, keys: List[str], timeout: "float | timedelta" = 10.0) -> bool:
        return self._client.call("check", {"keys": keys}, timeout)["exists"]

    def delete(self, key: str, timeout: "float | timedelta" = 10.0) -> bool:
        return self._client.call("delete", {"key": key}, timeout)["deleted"]

    def num_keys(self, timeout: "float | timedelta" = 10.0) -> int:
        return self._client.call("num_keys", {}, timeout)["count"]


# ----------------------------------------------------- pure logic (testing)
def quorum_compute(state: dict, opts: dict) -> dict:
    """Run the native lighthouse quorum computation on a synthetic state.

    For unit tests (reference pattern: src/lighthouse.rs:627-1071).
    """
    lib = _load()
    result = ctypes.c_char_p()
    err = ctypes.c_char_p()
    status = lib.tft_quorum_compute(
        json.dumps(state).encode(), json.dumps(opts).encode(),
        ctypes.byref(result), ctypes.byref(err),
    )
    err_s = _take_str(lib, err)
    result_s = _take_str(lib, result)
    _raise_for_status(status, err_s, "quorum_compute failed")
    return json.loads(result_s)


def compute_quorum_results(
    replica_id: str, group_rank: int, quorum: dict, init_sync: bool = True
) -> QuorumResult:
    """Run the native per-rank recovery-assignment computation.

    For unit tests (reference pattern: src/manager.rs:881-1108).
    """
    lib = _load()
    result = ctypes.c_char_p()
    err = ctypes.c_char_p()
    status = lib.tft_compute_quorum_results(
        replica_id.encode(), group_rank, json.dumps(quorum).encode(),
        1 if init_sync else 0, ctypes.byref(result), ctypes.byref(err),
    )
    err_s = _take_str(lib, err)
    result_s = _take_str(lib, result)
    _raise_for_status(status, err_s, "compute_quorum_results failed")
    return QuorumResult._from_json(json.loads(result_s))


def health_scores(windows: "Dict[str, list]", opts: dict) -> "Dict[str, float]":
    """Run the NATIVE straggler scoring on synthetic windows.

    Parity hook for tests: torchft_tpu/healthwatch.py carries the canonical
    Python implementation and tests pin the native one to it.
    """
    lib = _load()
    result = ctypes.c_char_p()
    err = ctypes.c_char_p()
    status = lib.tft_health_scores(
        json.dumps(windows).encode(), json.dumps(opts).encode(),
        ctypes.byref(result), ctypes.byref(err),
    )
    err_s = _take_str(lib, err)
    result_s = _take_str(lib, result)
    _raise_for_status(status, err_s, "health_scores failed")
    return json.loads(result_s)


def health_replay(script: list, opts: dict) -> dict:
    """Replay a scripted beat/tick sequence through the NATIVE health
    ledger on a synthetic clock; returns ``{"events", "ledger", "excluded"}``.

    ``script`` entries: ``{"t_ms": N, "replica_id": ..., "telemetry":
    {...}?}`` for beats, ``{"t_ms": N, "tick": true}`` for ticks. ``opts``
    is HealthOpts fields plus ``heartbeat_timeout_ms`` / ``min_replicas``.
    Parity hook for tests against the Python :class:`HealthLedger`.
    """
    lib = _load()
    result = ctypes.c_char_p()
    err = ctypes.c_char_p()
    status = lib.tft_health_replay(
        json.dumps(script).encode(), json.dumps(opts).encode(),
        ctypes.byref(result), ctypes.byref(err),
    )
    err_s = _take_str(lib, err)
    result_s = _take_str(lib, result)
    _raise_for_status(status, err_s, "health_replay failed")
    return json.loads(result_s)


def history_replay(jsonl_text: str) -> dict:
    """Parse a recorded-history JSONL through the NATIVE read path;
    returns ``{"events": [...], "summary": {...}}``.

    Accepts content or a path (plain or gzip'd) — both are funnelled
    through :func:`torchft_tpu.tracing.load_history`, the single loader
    shared with the ``trace history`` and ``policy replay`` CLIs, so the
    entry points can't drift apart again.

    Parity hook for tests: torchft_tpu.tracing.history_fold carries the
    canonical Python fold and tests pin the native summary to it (same
    convention as :func:`health_replay`).
    """
    from torchft_tpu.tracing import load_history

    events = load_history(jsonl_text)
    normalized = "\n".join(json.dumps(e) for e in events)
    lib = _load()
    result = ctypes.c_char_p()
    err = ctypes.c_char_p()
    status = lib.tft_history_replay(
        normalized.encode(), ctypes.byref(result), ctypes.byref(err)
    )
    err_s = _take_str(lib, err)
    result_s = _take_str(lib, result)
    _raise_for_status(status, err_s, "history_replay failed")
    return json.loads(result_s)
