"""Accelerator/platform helpers (reference: torchft utils.py:17-67).

The reference's utils provide stream-context and event helpers for
cuda/xpu; on TPU, JAX's async dispatch replaces user-managed streams, so the
helpers here cover the platform concerns this framework actually has:
forcing a virtual multi-device CPU platform for tests and dry runs, and
blocking on device work.
"""

from __future__ import annotations

import os
import re
from typing import Any

_FLAG = "xla_force_host_platform_device_count"


def probe_backend(timeout_s: float = 60.0) -> "tuple[str, str]":
    """Probe the default JAX backend in a SUBPROCESS; (status, detail).

    status: "accel" (an accelerator initializes), "cpu" (init works, CPU
    only), "crash" (init fails fast), "hung" (init never returned — the
    wedged-tunnel mode). The subprocess is the point: a wedged platform
    plugin hangs backend init forever, and only a killable child turns
    that into a bounded, reportable answer. Shared by bench.py's
    pre-flight probe and ``python -m torchft_tpu.doctor``.
    """
    import subprocess
    import sys

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print('PROBE', jax.default_backend(), len(d))"],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return "hung", f"backend init hung >{timeout_s:.0f}s"
    if out.returncode != 0:
        return "crash", out.stderr.strip()[-300:]
    # scan for the sentinel line: runtimes love writing log lines to stdout
    for line in reversed(out.stdout.splitlines()):
        if line.startswith("PROBE "):
            _, backend, n = line.split()
            status = "cpu" if backend == "cpu" else "accel"
            return status, f"{backend} ({n} device(s))"
    return "crash", f"probe printed no result: {out.stdout[-200:]!r}"


def ensure_responsive_backend(timeout_s: float = 240.0) -> "tuple[str, str]":
    """Probe the default backend; on a hung/crashed init, force the CPU
    platform so the caller can still run (degraded, but alive).

    The one fallback policy shared by bench.py and __graft_entry__.entry()
    — a single timeout story, so the bench and the compile check can never
    classify the same backend differently. Returns ``probe_backend``'s
    (status, detail); callers surface the degradation in their artifacts.
    Costs one extra backend init (~tens of seconds on TPU) in the healthy
    case — the price of never hanging a driver forever.
    """
    status, detail = probe_backend(timeout_s)
    if status in ("hung", "crash"):
        force_virtual_cpu_devices(1)
    return status, detail


def force_virtual_cpu_devices(n: int) -> None:
    """Force a virtual ``n``-device CPU platform.

    Must run before the first JAX backend initialisation (importing jax is
    fine — ``XLA_FLAGS`` is read at backend-init time). Overrides any
    pre-existing smaller device-count flag, and flips ``jax_platforms`` to
    cpu because platform plugins (e.g. a tunnelled single TPU chip) can take
    precedence over ``JAX_PLATFORMS=cpu`` in the environment.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if _FLAG in flags:
        def _bump(m: "re.Match[str]") -> str:
            return f"--{_FLAG}={max(n, int(m.group(1)))}"

        flags = re.sub(rf"--{_FLAG}=(\d+)", _bump, flags)
    else:
        flags = f"{flags} --{_FLAG}={n}".strip()
    os.environ["XLA_FLAGS"] = flags

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialised; caller's device check reports it


def enable_compilation_cache(cache_dir: "str | None" = None) -> str:
    """Turn on JAX's persistent compilation cache rooted at ``cache_dir``.

    Heavy compiles are the one operation that has wedged this image's
    tunnelled TPU backend (see docs/operations.md); with a persistent cache
    they happen once per toolchain instead of once per process, so the
    driver's bench run replays cached executables instead of re-risking the
    compile. Sets the env var too so child processes (sweep subprocesses,
    probe children) share the cache. Returns the directory used.
    """
    if cache_dir is None:
        cache_dir = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.dirname(__file__)),
                         ".jax_cache"),
        )
    os.makedirs(cache_dir, exist_ok=True)
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir

    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache everything: the point is never recompiling, not saving disk
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir


def np_dtype_from_str(name: str):
    """np.dtype for a dtype name, including ml_dtypes extended types
    (bfloat16, float8_*) that plain np.dtype() doesn't know."""
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def peak_flops_per_chip() -> float:
    """Dense bf16 peak FLOP/s of the local chip, by device kind.

    The MFU denominator for benchmarks. Unknown kinds (including the CPU
    test platform) get a nominal 1e12 so MFU-style numbers stay finite
    without pretending to be comparable.
    """
    import jax

    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, flops in (
        ("v5 lite", 197e12),   # v5e
        ("v5e", 197e12),
        ("v6 lite", 918e12),   # v6e / Trillium
        ("v6e", 918e12),
        ("v5p", 459e12),
        ("v5", 459e12),        # bare "v5" after lite/p checks: assume v5p
        ("v4", 275e12),
        ("v3", 123e12),
        ("v2", 45e12),
    ):
        if key in kind:
            return flops
    return 1e12


def synchronize(tree: Any) -> Any:
    """Block until every array in ``tree`` has been computed.

    The analog of the reference's ``utils.synchronize`` (utils.py:58-67):
    JAX dispatch is async, so callers that need a host-visible completion
    point (commit gates, timing) block on the arrays themselves.
    """
    import jax

    return jax.block_until_ready(tree)


def import_shard_map() -> Any:
    """Return a ``shard_map`` callable that accepts the current-API kwargs.

    Newer JAX exports ``jax.shard_map`` (with ``check_vma``); older
    releases only ship ``jax.experimental.shard_map.shard_map`` (with
    ``check_rep``). Every call site in this repo is written against the
    current API, so the fallback wrapper translates ``check_vma`` ->
    ``check_rep`` instead of each caller branching on the JAX version.
    """
    try:
        from jax import shard_map  # jax >= 0.6

        return shard_map
    except ImportError:
        pass
    import functools

    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    @functools.wraps(_legacy_shard_map)
    def _shard_map_compat(f: Any, **kwargs: Any) -> Any:
        if "check_vma" in kwargs:
            kwargs.setdefault("check_rep", kwargs.pop("check_vma"))
        return _legacy_shard_map(f, **kwargs)

    return _shard_map_compat
