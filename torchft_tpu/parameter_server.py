"""Prototype fault-tolerant parameter server on reconfigurable process groups.

Role-equivalent of the reference's ParameterServer (parameter_server.py:30-194):
no lighthouse involved — a lightweight HTTP handshake creates per-client
*sessions*, each backed by a fresh two-member process group (server rank 0,
client rank 1) bootstrapped through the server's KV store under a
session-unique prefix. The HTTP handler thread is hijacked to run the
server half of the session (reference parameter_server.py:84-108), so each
live session costs one thread and failures are isolated per-session: a dead
client only tears down its own PG.

Subclass and implement ``forward()`` with the per-session protocol (e.g.
broadcast current params, receive gradient pushes)."""

from __future__ import annotations

import json
import logging
import socket
import threading
import urllib.request
import uuid
from abc import ABC, abstractmethod
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from torchft_tpu.coordination import KvStoreServer
from torchft_tpu.process_group import ProcessGroup, ProcessGroupHost
from torchft_tpu.retry import RetryPolicy, retry_call

logger = logging.getLogger(__name__)

__all__ = ["ParameterServer"]


class ParameterServer(ABC):
    """Abstract FT parameter server.

    Usage::

        class MyPS(ParameterServer):
            def forward(self, rank, pg):     # server: rank == 0
                pg.broadcast([params], root=0).get_future().wait()

        ps = MyPS(port=0)
        # on the client:
        pg = ParameterServer.new_session(ps.address())   # rank 1
    """

    def __init__(self, port: int = 0, timeout: float = 60.0) -> None:
        self._timeout = timeout
        self._store = KvStoreServer("0.0.0.0:0")
        store_port = self._store.port
        self._sessions_lock = threading.Lock()
        self._sessions_live = 0
        ps = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, format: str, *args: object) -> None:  # noqa: A002
                logger.debug("ps http: " + format, *args)

            def do_POST(self) -> None:
                if self.path != "/new_session":
                    self.send_error(404)
                    return
                session_id = str(uuid.uuid4())
                host = self.server.server_name  # type: ignore[attr-defined]
                store_addr = (
                    f"{socket.gethostname()}:{store_port}/session/{session_id}"
                )
                body = json.dumps(
                    {"session_id": session_id, "store_addr": store_addr}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                self.wfile.flush()
                del host
                # Hijack this handler thread for the session's server half
                # (reference parameter_server.py:84-108).
                pg = ProcessGroupHost(timeout=ps._timeout)
                # Hard deadline on session SETUP: a client that handshakes
                # but never configures its PG would otherwise hold this
                # thread for however long the rendezvous internals block.
                # The watchdog aborts the PG at ps._timeout, turning the
                # wedge into an ordinary (logged, isolated) session error.
                # forward() is the user protocol and manages its own
                # timeouts through the PG, so the watchdog is disarmed the
                # moment configure returns.
                watchdog = threading.Timer(ps._timeout, pg.abort)
                watchdog.daemon = True
                with ps._sessions_lock:
                    ps._sessions_live += 1
                try:
                    watchdog.start()
                    try:
                        pg.configure(store_addr, 0, 2, quorum_id=0)
                    finally:
                        watchdog.cancel()
                    ps.forward(0, pg)
                except Exception:  # noqa: BLE001 — per-session isolation
                    logger.exception("session %s failed", session_id)
                finally:
                    pg.shutdown()
                    with ps._sessions_lock:
                        ps._sessions_live -= 1

        self._server = ThreadingHTTPServer(("0.0.0.0", port), _Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="ps_http"
        )
        self._thread.start()

    def address(self) -> str:
        return f"http://{socket.gethostname()}:{self._server.server_port}"

    def active_sessions(self) -> int:
        """Sessions currently holding a hijacked handler thread (setup or
        forward()); observability for tests and ops."""
        with self._sessions_lock:
            return self._sessions_live

    @classmethod
    def new_session(
        cls,
        address: str,
        timeout: float = 60.0,
        retry_policy: Optional[RetryPolicy] = None,
    ) -> ProcessGroup:
        """Client side: open a session against a running server; returns a
        configured two-member PG where the caller is rank 1
        (reference parameter_server.py:110-139).

        The HTTP handshake retries under the standard ``TORCHFT_RETRY_*``
        policy (``retry_policy`` overrides): a single connection refused
        while the server is still binding its port is backoff-and-retry,
        not fatal.  ``timeout`` is the hard wall-clock budget across all
        handshake attempts AND the PG configure that follows."""
        policy = retry_policy if retry_policy is not None else RetryPolicy.from_env()

        def handshake(remaining: float) -> dict:
            with urllib.request.urlopen(
                urllib.request.Request(f"{address}/new_session", method="POST"),
                timeout=max(remaining, 0.05),
            ) as resp:
                return json.loads(resp.read().decode())

        info = retry_call(
            handshake,
            policy=policy,
            timeout=timeout,
            retryable=(OSError, TimeoutError, ValueError),
            # a refused/reset connect usually means the server (re)started:
            # full jitter de-packs the reconnect herd (see retry.py)
            full_jitter_on=(ConnectionError,),
        )
        pg = ProcessGroupHost(timeout=timeout)
        pg.configure(info["store_addr"], 1, 2, quorum_id=0)
        return pg

    @abstractmethod
    def forward(self, rank: int, pg: ProcessGroup) -> None:
        """Per-session protocol; runs with the session PG configured.
        ``rank`` is 0 on the server's hijacked handler thread."""

    def shutdown(self) -> None:
        self._server.shutdown()
        # release the listening socket (shutdown() only stops serve_forever);
        # without this the port stays bound until process exit
        self._server.server_close()
        self._store.shutdown()
