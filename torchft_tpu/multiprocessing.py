"""Pipe plumbing for subprocess-isolated process groups.

Role-equivalent of the reference's torchft/multiprocessing.py:16-37
(`_MonitoredPipe`): a thin wrapper over a multiprocessing Connection that
adds recv timeouts and passes exceptions shipped over the pipe through to
the caller. Used by :class:`torchft_tpu.process_group.ProcessGroupBaby` to
talk to its child process.
"""

from __future__ import annotations

import threading
from datetime import timedelta
from typing import Any, Optional, Union

__all__ = ["_MonitoredPipe"]


class _MonitoredPipe:
    """Connection wrapper with recv timeout + exception passthrough.

    ``conn`` must quack like ``multiprocessing.connection.Connection``
    (send / recv / poll / close) — the thread-backed dummy context's pipe
    (multiprocessing_dummy_context._DummyConnection) also qualifies, so Baby
    process groups can run threaded in tests.
    """

    def __init__(self, conn: Any) -> None:
        self._conn = conn
        self._lock = threading.Lock()

    def send(self, obj: object) -> None:
        with self._lock:
            self._conn.send(obj)

    def recv(self, timeout: Union[float, timedelta]) -> object:
        """Receive one object; raises TimeoutError if nothing arrives in
        ``timeout`` seconds, re-raises any Exception instance received."""
        if isinstance(timeout, timedelta):
            timeout = timeout.total_seconds()
        if not self._conn.poll(timeout):
            raise TimeoutError(f"pipe recv timed out after {timeout}s")
        item = self._conn.recv()
        if isinstance(item, Exception):
            raise item
        return item

    def poll(self, timeout: Optional[float] = None) -> bool:
        return self._conn.poll(timeout)

    def close(self) -> None:
        try:
            self._conn.close()
        except OSError:
            pass

    def closed(self) -> bool:
        return getattr(self._conn, "closed", False)
